"""Unit class catalog.

Rebuild of veles/unit_registry.py:51-176: a metaclass records every Unit
subclass with a stable UUID (``__id__``) so workflows can be exported and
re-instantiated by id (the C++ runner's unit factory keys on these UUIDs —
ref: libVeles/src/unit_factory.cc:1-65), and so tooling can enumerate the
full unit catalog.  :class:`MappedUnitRegistry` adds named factories
(normalizers, loaders, publishing backends…).
"""

import uuid

from veles_tpu.distributable import Distributable

#: deterministic namespace so a class's UUID is stable across processes —
#: required for package_export archives to be loadable anywhere.
_NAMESPACE = uuid.UUID("6ba7b812-9dad-11d1-80b4-00c04fd430c8")


class UnitRegistry(type):
    """Metaclass cataloguing all Unit subclasses
    (ref: veles/unit_registry.py:51-176)."""

    #: name -> class for every registered (non-hidden) unit class
    units = {}
    #: str(uuid) -> class
    by_id = {}

    def __init__(cls, name, bases, namespace):
        super(UnitRegistry, cls).__init__(name, bases, namespace)
        # every class gets a stable id (tooling reads .id on any unit);
        # hidden classes just stay out of the catalog
        cls.__id__ = namespace.get(
            "__id__", str(uuid.uuid5(_NAMESPACE, cls.__module__ + "." + name)))
        if namespace.get("hide_from_registry", False):
            return
        UnitRegistry.units[name] = cls
        UnitRegistry.by_id[cls.__id__] = cls


class MappedUnitRegistry(UnitRegistry):
    """Metaclass for families addressed by a ``MAPPING`` name, e.g.
    normalizers (ref: veles/normalization.py:110) and loaders.

    Subclass hierarchies set ``mapping_root`` truthy on the base class;
    concrete classes declare ``MAPPING = "name"``.
    """

    registries = {}

    def __init__(cls, name, bases, namespace):
        super(MappedUnitRegistry, cls).__init__(name, bases, namespace)
        mapping = namespace.get("MAPPING")
        if mapping is None:
            return
        # find the hierarchy root: nearest base that *declares*
        # mapping_root in its own body (inherited copies don't count, or
        # intermediate bases would capture the family)
        for base in cls.__mro__[1:]:
            if vars(base).get("mapping_root", False):
                MappedUnitRegistry.registries.setdefault(
                    base.__name__, {})[mapping] = cls
                break

    @staticmethod
    def get_factory(root_name, mapping):
        fam = MappedUnitRegistry.registries.get(root_name, {})
        try:
            return fam[mapping]
        except KeyError:
            raise KeyError("no %r registered under %s (have: %s)" % (
                mapping, root_name, sorted(fam)))


class RegisteredDistributable(Distributable, metaclass=UnitRegistry):
    """Distributable whose subclasses are auto-catalogued."""
    hide_from_registry = True
