"""genetics — GA hyper-parameter optimization (L9).

Rebuild of veles/genetics/: ``Range`` markers in the config tree are
the search space; individuals are evaluated by re-running the workflow
CLI with ``-c`` overrides and reading ``--result-file`` fitness.
"""

from veles_tpu.genetics.core import (  # noqa: F401
    Choice, Chromosome, Population, Range, Tuneable, collect_tuneables,
    fix_config)
from veles_tpu.genetics.optimizer import (  # noqa: F401
    GeneticsOptimizer, SubprocessEvaluator, fitness_from_results)
