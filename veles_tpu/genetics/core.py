"""Genetic-algorithm core — tuneables, chromosomes, population.

Rebuild of veles/genetics/ (config.py:45-128 Range/Tuneable markers in
the config tree; core.py:133,371 Chromosome/Population).  The GA itself
is pure host-side Python; fitness evaluation happens by running the
model workflow (one CLI subprocess per individual — see optimizer.py),
exactly the reference's evaluation-by-subprocess contract
(genetics/optimization_workflow.py:70,298).
"""

import numpy

from veles_tpu.config import Config


class Tuneable:
    """Base marker placed in the config tree (ref: genetics/config.py:45)."""

    def __init__(self, default):
        self.default = default

    def random(self, rng):
        raise NotImplementedError()

    def mutate(self, value, rng, scale):
        raise NotImplementedError()

    def clip(self, value):
        return value


class Range(Tuneable):
    """Numeric tuneable in [min_value, max_value]
    (ref: genetics/config.py Range)."""

    def __init__(self, default, min_value, max_value):
        super(Range, self).__init__(default)
        self.min_value = min_value
        self.max_value = max_value
        self._integer = all(
            isinstance(v, (int, numpy.integer)) and not isinstance(v, bool)
            for v in (default, min_value, max_value))

    def clip(self, value):
        value = min(max(value, self.min_value), self.max_value)
        return int(round(value)) if self._integer else float(value)

    def random(self, rng):
        return self.clip(
            rng.uniform(self.min_value, self.max_value))

    def mutate(self, value, rng, scale):
        span = (self.max_value - self.min_value) * scale
        return self.clip(value + rng.normal(0.0, max(span, 1e-12)))

    def __repr__(self):
        return "Range(%r, %r, %r)" % (self.default, self.min_value,
                                      self.max_value)


class Choice(Tuneable):
    """Categorical tuneable (capability extension of the same marker
    family)."""

    def __init__(self, default, choices):
        super(Choice, self).__init__(default)
        self.choices = list(choices)

    def random(self, rng):
        return self.choices[int(rng.integers(len(self.choices)))]

    def mutate(self, value, rng, scale):
        if rng.random() < max(scale, 0.1):
            return self.random(rng)
        return value


def collect_tuneables(cfg, path="root"):
    """Walk the config tree for Tuneable markers → [(dotted_path, t)]
    (ref: genetics/config.py fix_config walk)."""
    found = []
    for k, v in vars(cfg).items():
        if k.startswith("_") and k.endswith("_"):
            continue
        p = "%s.%s" % (path, k)
        if isinstance(v, Config):
            found.extend(collect_tuneables(v, p))
        elif isinstance(v, Tuneable):
            found.append((p, v))
    return sorted(found)


def fix_config(cfg):
    """Replace remaining Tuneable markers with their defaults so a
    workflow can run un-tuned (ref: genetics/config.py:164)."""
    for k, v in list(vars(cfg).items()):
        if k.startswith("_") and k.endswith("_"):
            continue
        if isinstance(v, Config):
            fix_config(v)
        elif isinstance(v, Tuneable):
            setattr(cfg, k, v.default)


class Chromosome:
    """One config instantiation (ref: genetics/core.py:133)."""

    __slots__ = ("genes", "fitness")

    def __init__(self, genes):
        self.genes = list(genes)
        self.fitness = None

    def overrides(self, tuneables):
        """CLI ``-c`` snippets applying this individual's genes."""
        return ["%s = %r" % (path, g)
                for (path, _), g in zip(tuneables, self.genes)]


class Population:
    """GA population: tournament selection, blend crossover, gaussian
    mutation, elitism (ref: genetics/core.py:371 — the reference's
    roulette+two-point machinery, re-specialised for the small numeric
    gene vectors hyper-parameter search actually uses)."""

    def __init__(self, tuneables, size=8, seed=42, mutation_scale=0.15,
                 crossover_rate=0.9, elite=1):
        if not tuneables:
            raise ValueError("no Tuneable markers found in the config")
        self.tuneables = tuneables
        self.size = size
        self.rng = numpy.random.default_rng(seed)
        self.mutation_scale = mutation_scale
        self.crossover_rate = crossover_rate
        self.elite = elite
        first = Chromosome([t.default for _, t in tuneables])
        self.individuals = [first] + [
            Chromosome([t.random(self.rng) for _, t in tuneables])
            for _ in range(size - 1)]
        self.generation = 0
        self.best = None

    def _tournament(self, k=2):
        picks = self.rng.choice(len(self.individuals), size=k,
                                replace=False)
        return max((self.individuals[i] for i in picks),
                   key=lambda c: c.fitness)

    def evolve(self):
        """One generation step; every individual must have a fitness."""
        assert all(c.fitness is not None for c in self.individuals)
        ranked = sorted(self.individuals, key=lambda c: c.fitness,
                        reverse=True)
        if self.best is None or ranked[0].fitness > self.best.fitness:
            self.best = ranked[0]
        nxt = [Chromosome(list(c.genes)) for c in ranked[:self.elite]]
        for c in nxt:
            c.fitness = None
        while len(nxt) < self.size:
            a, b = self._tournament(), self._tournament()
            genes = []
            for (path, t), ga, gb in zip(self.tuneables, a.genes, b.genes):
                if self.rng.random() < self.crossover_rate \
                        and isinstance(t, Range):
                    w = self.rng.random()
                    g = t.clip(w * ga + (1 - w) * gb)  # blend crossover
                else:
                    g = ga if self.rng.random() < 0.5 else gb
                if self.rng.random() < 0.3:
                    g = t.mutate(g, self.rng, self.mutation_scale)
                genes.append(g)
            nxt.append(Chromosome(genes))
        self.individuals = nxt
        self.generation += 1
