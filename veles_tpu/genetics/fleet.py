"""Distributed GA evaluation over the elastic coordinator.

Rebuild of the reference's distributed genetics (the GA master
generated individuals as slave jobs,
veles/genetics/optimization_workflow.py:298): the optimizer pushes each
generation's individuals into a :class:`Coordinator` as jobs
(payload = config overrides + seed), workers evaluate them — normally
by the same CLI-subprocess contract as local mode — and send the
fitness back as the update.  Between generations workers park on the
coordinator's wait/resume push (no polling), so one fleet spans the
whole optimization like the reference's master/slave GA.

Master side: :class:`FleetJobSource` (the IDistributable face the
coordinator consumes) + :class:`CoordinatorEvaluator` (plugs into
``GeneticsOptimizer`` as its batch evaluator).
Worker side: :func:`serve_fleet_worker` (blocking; pass the same
``evaluate`` callable the local optimizer would use).
"""

import asyncio
import queue
import threading

from veles_tpu.logger import Logger


class FleetJobSource(Logger):
    """Thread-safe job queue with the coordinator's workflow face.

    Jobs: ``{"job_id", "overrides", "seed"}``; updates:
    ``{"job_id", "fitness"}``.  ``finish()`` ends the run (workers get
    terminate); until then an empty queue just parks workers.
    """

    def __init__(self, checksum="genetics-fleet"):
        super(FleetJobSource, self).__init__()
        self._checksum = checksum
        self._jobs = queue.Queue()
        self._in_flight = {}     # job_id -> (job, worker_id)
        self._results = {}       # job_id -> fitness|None
        self._result_event = threading.Event()
        self._finished = False
        self._lock = threading.Lock()
        self._next_id = 0

    # -- optimizer side --------------------------------------------------------

    def submit(self, overrides, seed):
        """Enqueue one individual; returns its job id."""
        with self._lock:
            jid = self._next_id
            self._next_id += 1
        self._jobs.put({"job_id": jid, "overrides": list(overrides),
                        "seed": int(seed)})
        return jid

    def wait_all(self, job_ids, timeout=None):
        """Block until every job id has a result; returns
        {job_id: fitness|None}."""
        import time
        deadline = None if timeout is None else time.time() + timeout
        while True:
            with self._lock:
                if all(j in self._results for j in job_ids):
                    return {j: self._results[j] for j in job_ids}
            if deadline is not None and time.time() > deadline:
                raise TimeoutError("fleet evaluation timed out")
            self._result_event.wait(0.1)
            self._result_event.clear()

    def finish(self):
        self._finished = True

    # -- coordinator face (ref IDistributable, distributable.py:222) ----------

    def checksum(self):
        return self._checksum

    def has_more_jobs(self):
        return not self._jobs.empty()

    def all_jobs_done(self):
        return self._finished

    def generate_data_for_slave(self, worker_id):
        job = self._jobs.get_nowait()
        with self._lock:
            self._in_flight[job["job_id"]] = (job, worker_id)
        return job

    def apply_data_from_slave(self, data, worker_id):
        jid = data["job_id"]
        with self._lock:
            self._in_flight.pop(jid, None)
            self._results[jid] = data.get("fitness")
        self._result_event.set()

    def drop_slave(self, worker_id):
        """Requeue the dead worker's in-flight individuals."""
        with self._lock:
            requeue = [job for jid, (job, wid) in
                       list(self._in_flight.items()) if wid == worker_id]
            for job in requeue:
                del self._in_flight[job["job_id"]]
        for job in requeue:
            self._jobs.put(job)
        if requeue:
            self.info("requeued %d individual(s) from dropped worker %s",
                      len(requeue), worker_id)


class CoordinatorEvaluator(Logger):
    """Batch evaluator backed by a coordinator fleet.

    Plugs into :class:`~veles_tpu.genetics.optimizer.GeneticsOptimizer`
    (which prefers ``evaluate_batch`` when the evaluator has one).
    Owns the coordinator: it runs on a background asyncio thread for
    the whole optimization.
    """

    def __init__(self, checksum="genetics-fleet", host="127.0.0.1",
                 port=0, job_timeout=600.0, result_timeout=None):
        super(CoordinatorEvaluator, self).__init__()
        from veles_tpu.parallel.coordinator import Coordinator
        self.source = FleetJobSource(checksum)
        self.result_timeout = result_timeout
        self._coord = Coordinator(self.source, host=host, port=port,
                                  job_timeout=job_timeout)
        self._loop = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="genetics-fleet")
        self._thread.start()
        self._started.wait(10)
        self.port = self._coord.port

    def _serve(self):
        async def main():
            await self._coord.start()
            self._loop = asyncio.get_event_loop()
            self._started.set()
            await self._coord.wait_finished()
            await self._coord.stop()

        asyncio.run(main())

    def evaluate_batch(self, batch):
        """batch: [(overrides, seed)] -> [fitness|None] in order."""
        ids = [self.source.submit(ov, seed) for ov, seed in batch]
        # wake workers parked since the previous generation drained —
        # submit() runs on the optimizer thread, outside the protocol
        # flow the coordinator's own wake piggybacks on
        self._coord.notify_jobs()
        results = self.source.wait_all(ids, timeout=self.result_timeout)
        return [results[i] for i in ids]

    def __call__(self, overrides, seed):
        return self.evaluate_batch([(overrides, seed)])[0]

    def close(self):
        """End the optimization: workers get terminate, the coordinator
        drains and stops."""
        self.source.finish()
        self._coord.request_stop()
        self._thread.join(15)


class _FleetWorkerFace:
    """Worker-side workflow face: do_job evaluates one individual."""

    def __init__(self, evaluate, checksum):
        self._evaluate = evaluate
        self._checksum = checksum

    def checksum(self):
        return self._checksum

    def do_job(self, job, update, callback):
        fitness = self._evaluate(job["overrides"], seed=job["seed"])
        callback({"job_id": job["job_id"], "fitness": fitness})


def serve_fleet_worker(address, evaluate, checksum="genetics-fleet",
                       worker_id=None, max_reconnects=10):
    """Blocking fleet worker: joins the coordinator at ``address`` and
    evaluates individuals with ``evaluate(overrides, seed)`` (same
    contract as the local :class:`SubprocessEvaluator`)."""
    from veles_tpu.parallel.coordinator import WorkerClient

    async def main():
        client = WorkerClient(_FleetWorkerFace(evaluate, checksum),
                              address, worker_id=worker_id,
                              max_reconnects=max_reconnects)
        await client.run()

    asyncio.run(main())
