"""GeneticsOptimizer — hyper-parameter search over CLI subprocesses.

Rebuild of veles/genetics/optimization_workflow.py:70,298: each
individual is evaluated by re-running ``python -m veles_tpu <workflow>
<config>`` with ``-c`` overrides for its genes and ``--result-file``
for the fitness, exactly the reference's subprocess contract.  The
evaluator can also be swapped out (tests inject a python callable).
"""

import logging
import sys

from veles_tpu.cli_exec import run_cli_collect_results
from veles_tpu.genetics.core import Population, collect_tuneables

log = logging.getLogger("genetics")

#: result-file keys tried (in order) when deriving fitness; all are
#: minimized, so fitness = -value
FITNESS_KEYS = ("EvaluationFitness", "min_validation_n_err",
                "validation_error_pct", "validation_loss", "RMSE")


def fitness_from_results(results):
    """Fitness (maximized) from a --result-file dict: an explicit
    ``EvaluationFitness`` wins; error-like metrics are negated
    (ref: genetics read of --result-file JSON)."""
    if "EvaluationFitness" in results:
        return float(results["EvaluationFitness"])
    for key in FITNESS_KEYS[1:]:
        if key in results:
            return -float(results[key])
    raise KeyError(
        "no fitness key in result file (have: %s; expected one of %s)"
        % (sorted(results), list(FITNESS_KEYS)))


class SubprocessEvaluator:
    """Runs one individual through the CLI (ref subprocess exec:
    ensemble/base_workflow.py:135-152 — genetics uses the same shape)."""

    def __init__(self, workflow_file, config_file=None, base_overrides=(),
                 extra_argv=(), timeout=None):
        self.workflow_file = workflow_file
        self.config_file = config_file
        self.base_overrides = list(base_overrides)
        self.extra_argv = list(extra_argv)
        self.timeout = timeout

    def __call__(self, overrides, seed):
        argv = [sys.executable, "-m", "veles_tpu", self.workflow_file]
        if self.config_file:
            argv.append(self.config_file)
        for ov in self.base_overrides + list(overrides):
            argv += ["-c", ov]
        argv += ["--seed", str(seed)] + self.extra_argv
        results = run_cli_collect_results(argv, timeout=self.timeout)
        if results is None:
            return None
        try:
            return fitness_from_results(results)
        except KeyError as e:
            log.warning("individual produced no fitness: %s", e)
            return None


class GeneticsOptimizer:
    """The population loop (ref: genetics/optimization_workflow.py:298).

    ``evaluate(overrides, seed) -> fitness|None`` is pluggable; failed
    individuals get the worst fitness seen so far (the reference dropped
    them from the next generation the same way).
    """

    def __init__(self, config_root, evaluate, size=8, generations=4,
                 seed=42):
        self.tuneables = collect_tuneables(config_root)
        self.population = Population(self.tuneables, size=size, seed=seed)
        self.evaluate = evaluate
        self.generations = generations
        self.history = []

    def _evaluate_generation(self, gen):
        """Fitness for every unevaluated individual — one at a time
        locally, or as a whole generation of coordinator jobs when the
        evaluator is fleet-backed (``evaluate_batch``, the reference's
        distributed GA: individuals were slave jobs,
        genetics/optimization_workflow.py:298)."""
        pending = [(i, indiv) for i, indiv in
                   enumerate(self.population.individuals)
                   if indiv.fitness is None]
        if hasattr(self.evaluate, "evaluate_batch"):
            batch = [(indiv.overrides(self.tuneables),
                      1000 + gen * 100 + i) for i, indiv in pending]
            fits = self.evaluate.evaluate_batch(batch)
        else:
            fits = [self.evaluate(indiv.overrides(self.tuneables),
                                  seed=1000 + gen * 100 + i)
                    for i, indiv in pending]
        for (i, indiv), fit in zip(pending, fits):
            indiv.fitness = fit
            log.info("gen %d individual %d: fitness %s  genes %s",
                     gen, i, fit, indiv.genes)
        return [f for f in fits if f is not None]

    def run(self):
        for gen in range(self.generations):
            # note: elites are re-evaluated each generation — fitness
            # from a short training run is noisy, and a lucky seed
            # must not colonize the population forever
            evaluated = self._evaluate_generation(gen)
            worst = min(evaluated) if evaluated else None
            fallback = (worst if worst is not None else 0.0) - 1.0
            for indiv in self.population.individuals:
                if indiv.fitness is None:
                    indiv.fitness = fallback
            self.history.append(max(
                c.fitness for c in self.population.individuals))
            self.population.evolve()
            log.info("gen %d done: best fitness %s genes %s", gen,
                     self.population.best.fitness,
                     self.population.best.genes)
        best = self.population.best
        return {
            "best_fitness": best.fitness,
            "best_genes": {path: g for (path, _), g in
                           zip(self.tuneables, best.genes)},
            "history": self.history,
            "generations": self.generations,
        }
