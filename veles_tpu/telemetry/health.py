"""Training-health monitoring — numeric anomaly detection with policy.

The reference framework surfaced training health as a human reading
the console: a NaN loss scrolled past in the epoch printout and the
operator killed the run (veles/znicz decision printed, nothing acted).
At production scale nobody watches; this module makes model health a
first-class, *acted-on* signal:

- the jitted trainer steps (:mod:`veles_tpu.models.gd`) compute a
  cheap health vector in-graph — global grad-norm, weight-norm,
  update ratio ``|Δw|/|w|`` and a NaN/Inf flag — and return it as aux
  output, so detection costs one tiny device→host read, not a second
  pass over the parameters;
- :class:`HealthMonitor` (the process-wide :data:`monitor`) receives
  those readings, exports them as ``veles_health_*`` registry series,
  and applies the configured policy;
- the ``skip_step`` policy is additionally enforced *inside* the
  jitted step (``jnp.where`` selecting the pre-step parameters), so a
  non-finite update never reaches the weights even though the host
  only learns about it after the dispatch.

Policy (``root.common.health.policy``):

- ``warn`` (default) — count + log, training continues;
- ``skip_step`` — the anomalous update is dropped in-graph (params
  and epoch accounting keep their pre-step values), counted, logged;
- ``halt`` — the monitor latches ``halted``; the trainer stops the
  workflow gracefully (``GET /healthz`` then answers 503 — the
  process stays up for forensics, it does not crash).

Loss-history divergence (EMA + patience) is fed by the decision unit
at epoch boundaries through :meth:`HealthMonitor.observe_loss`.
"""

import logging
import math
import threading

from veles_tpu.telemetry.registry import metrics

POLICIES = ("warn", "skip_step", "halt")

#: status levels for the ``veles_health_status`` gauge / ``/healthz``
OK, DEGRADED, HALTED = 0, 1, 2
STATUS_NAMES = {OK: "ok", DEGRADED: "degraded", HALTED: "halted"}

log = logging.getLogger("health")


def health_config():
    """The effective ``root.common.health.*`` knobs (read per call so
    tests and ``-c`` overrides apply).  Host-side knobs take effect
    immediately; ``enabled``/``policy`` are also baked into the
    jitted trainer steps at trace time — the trainer detects a change
    and rebuilds them on the next dispatch
    (``GradientDescent._maybe_invalidate_steps``)."""
    from veles_tpu.config import root
    cfg = root.common.health
    policy = str(cfg.get("policy", "warn"))
    if policy not in POLICIES:
        log.warning("unknown health policy %r - falling back to 'warn'",
                    policy)
        policy = "warn"
    return {
        "enabled": bool(cfg.get("enabled", True)),
        "policy": policy,
        #: host-side explosion warning threshold (None = off)
        "grad_norm_max": cfg.get("grad_norm_max"),
        #: read health back to host every N train dispatches (the
        #: in-graph skip_step guard is always per step regardless)
        "sync_every": int(cfg.get("sync_every", 1)),
        "ema_beta": float(cfg.get("ema_beta", 0.9)),
        "divergence_tolerance": float(
            cfg.get("divergence_tolerance", 1.5)),
        "divergence_patience": int(cfg.get("divergence_patience", 3)),
    }


def _series():
    return {
        "nonfinite": metrics.counter(
            "veles_health_nonfinite_total",
            "train steps whose loss or gradients were NaN/Inf"),
        "skipped": metrics.counter(
            "veles_health_steps_skipped_total",
            "anomalous updates dropped in-graph by the skip_step "
            "policy"),
        "halts": metrics.counter(
            "veles_health_halts_total",
            "times the halt policy latched (non-finite step or loss "
            "divergence)"),
        "divergence": metrics.counter(
            "veles_health_divergence_events_total",
            "loss-divergence events (loss above EMA*tolerance for "
            "'patience' consecutive observations)"),
        "explosions": metrics.counter(
            "veles_health_grad_explosions_total",
            "finite steps whose global grad-norm exceeded "
            "root.common.health.grad_norm_max"),
        "grad_norm": metrics.gauge(
            "veles_health_grad_norm",
            "last observed global gradient L2 norm"),
        "weight_norm": metrics.gauge(
            "veles_health_weight_norm",
            "last observed global parameter L2 norm"),
        "update_ratio": metrics.gauge(
            "veles_health_update_ratio",
            "last observed |param update| / |param| ratio"),
        "loss": metrics.gauge(
            "veles_health_loss", "last observed training loss"),
        "loss_ema": metrics.gauge(
            "veles_health_loss_ema",
            "EMA of the per-epoch loss fed to divergence detection"),
        "status": metrics.gauge(
            "veles_health_status",
            "health policy state: 0 ok, 1 degraded, 2 halted"),
    }


class HealthMonitor:
    """Aggregates health readings, applies the policy, answers
    ``/healthz``.  Thread-safe; one process-wide instance
    (:data:`monitor`) mirrors the registry convention."""

    #: log the first few anomalies verbosely, then every Nth
    WARN_HEAD, WARN_EVERY = 5, 100

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = None
        self.reset()

    def reset(self):
        """Forget observation state (counters in the registry stay —
        they are monotonic; tests assert on deltas)."""
        with self._lock:
            self.status = OK
            self.steps = 0
            self.nonfinite_total = 0
            self.skipped_total = 0
            self.halts_total = 0
            self.divergence_events = 0
            self.last = {}
            self.loss_ema = None
            self.divergence_streak = 0
            self._warned = 0

    def _m(self):
        if self._metrics is None:
            self._metrics = _series()
        return self._metrics

    @property
    def halted(self):
        with self._lock:
            return self.status == HALTED

    @property
    def status_name(self):
        with self._lock:
            return STATUS_NAMES[self.status]

    def _warn(self, msg, *args):
        self._warned += 1
        if self._warned <= self.WARN_HEAD \
                or self._warned % self.WARN_EVERY == 0:
            log.warning(msg + " (occurrence %d)", *(args
                                                    + (self._warned,)))

    def on_train_step(self, grad_norm, weight_norm, update_ratio,
                      nonfinite, loss=None, unit=None):
        """One (or one span of) train step(s) observed.  ``nonfinite``
        is the count of anomalous steps in the reading.  Returns the
        action taken: ``ok`` / ``warn`` / ``skip_step`` / ``halt``."""
        cfg = health_config()
        m = self._m()
        action = "ok"
        with self._lock:
            self.steps += 1
            self.last = {"grad_norm": grad_norm,
                         "weight_norm": weight_norm,
                         "update_ratio": update_ratio,
                         "loss": loss, "unit": unit}
            m["grad_norm"].set(grad_norm)
            m["weight_norm"].set(weight_norm)
            m["update_ratio"].set(update_ratio)
            if loss is not None:
                m["loss"].set(loss)
            if nonfinite and nonfinite > 0:
                n = int(nonfinite)
                self.nonfinite_total += n
                m["nonfinite"].inc(n)
                if cfg["policy"] == "halt":
                    self.status = HALTED
                    self.halts_total += 1
                    m["halts"].inc()
                    action = "halt"
                elif cfg["policy"] == "skip_step":
                    self.skipped_total += n
                    m["skipped"].inc(n)
                    self.status = max(self.status, DEGRADED)
                    action = "skip_step"
                else:
                    self.status = max(self.status, DEGRADED)
                    action = "warn"
                self._warn(
                    "non-finite training step (x%d) in %s - policy %s",
                    n, unit or "?", cfg["policy"])
            elif cfg["grad_norm_max"] is not None \
                    and math.isfinite(grad_norm) \
                    and grad_norm > float(cfg["grad_norm_max"]):
                m["explosions"].inc()
                self.status = max(self.status, DEGRADED)
                action = "warn"
                self._warn(
                    "gradient explosion: |g|=%.3g > %.3g in %s",
                    grad_norm, float(cfg["grad_norm_max"]),
                    unit or "?")
            m["status"].set(self.status)
        return action

    def observe_loss(self, loss):
        """Epoch-level loss for divergence detection (EMA + patience;
        fed by the decision unit).  Returns ``ok`` / ``diverging`` /
        ``halt``."""
        cfg = health_config()
        m = self._m()
        action = "ok"
        with self._lock:
            loss = float(loss)
            finite = math.isfinite(loss)
            if self.loss_ema is None:
                if finite:
                    self.loss_ema = loss
                    m["loss_ema"].set(loss)
                return "ok"
            threshold = self.loss_ema * cfg["divergence_tolerance"] \
                + 1e-12
            if not finite or loss > threshold:
                self.divergence_streak += 1
            else:
                self.divergence_streak = 0
            if finite:
                beta = cfg["ema_beta"]
                self.loss_ema = beta * self.loss_ema \
                    + (1.0 - beta) * loss
                m["loss_ema"].set(self.loss_ema)
            if self.divergence_streak >= cfg["divergence_patience"]:
                self.divergence_streak = 0  # re-arm
                self.divergence_events += 1
                m["divergence"].inc()
                self.status = max(self.status, DEGRADED)
                action = "diverging"
                if cfg["policy"] == "halt":
                    self.status = HALTED
                    self.halts_total += 1
                    m["halts"].inc()
                    action = "halt"
                self._warn(
                    "loss divergence: %.4g above EMA %.4g for %d "
                    "epochs - policy %s", loss, self.loss_ema,
                    cfg["divergence_patience"], cfg["policy"])
            m["status"].set(self.status)
        return action

    def state(self):
        """Plain-dict state for ``/healthz``, the flight recorder and
        bench.py."""
        with self._lock:
            return {
                "status": STATUS_NAMES[self.status],
                "policy": health_config()["policy"],
                "steps_observed": self.steps,
                "nonfinite_total": self.nonfinite_total,
                "skipped_total": self.skipped_total,
                "halts_total": self.halts_total,
                "divergence_events": self.divergence_events,
                "loss_ema": self.loss_ema,
                "divergence_streak": self.divergence_streak,
                "last": dict(self.last),
            }

    def summary_line(self):
        """One-line digest for ``Workflow.print_stats`` (None when no
        training was observed)."""
        with self._lock:
            if not self.steps:
                return None
            last = self.last
            return ("health: %s  steps %d  nonfinite %d  skipped %d  "
                    "divergence %d  |g| %.3g  |w| %.3g  du/u %.3g"
                    % (STATUS_NAMES[self.status], self.steps,
                       self.nonfinite_total, self.skipped_total,
                       self.divergence_events,
                       last.get("grad_norm") or 0.0,
                       last.get("weight_norm") or 0.0,
                       last.get("update_ratio") or 0.0))


#: process-wide monitor (the ``/healthz`` surface)
monitor = HealthMonitor()
