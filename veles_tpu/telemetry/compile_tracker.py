"""JIT compile tracking — make XLA (re)compilation a first-class
metric.

Every jitted entry point in the framework (fused workflow segments,
trainer steps, serving prefill / slot decode, the ``generate()``
decode family) is wrapped with :func:`track_jit`; the wrapper detects
compilations by watching the jitted callable's executable-cache size
grow across a call (``jax.jit`` exposes ``_cache_size()``), so

- first-call compile time per entry point becomes a gauge,
- recompile counts (new shapes / dtypes hitting the same entry point)
  become a counter — the "why is the server stalling" answer that raw
  wall timers can't give,
- each detected compile also lands in the span log as a
  ``jit.compile`` event, so Chrome traces show compile gaps inline.

:func:`maybe_profiler_trace` is the opt-in ``jax.profiler`` toggle:
set ``root.common.trace.profiler_dir`` and every ``Workflow.run()``
writes a TensorBoard-loadable device trace alongside the host spans.
"""

import contextlib
import functools
import time

from veles_tpu.logger import events
from veles_tpu.telemetry.registry import metrics


def _compile_metrics():
    return (
        metrics.counter(
            "veles_jit_compiles_total",
            "XLA compilations per jitted entry point (first call + "
            "every recompile on a new shape/dtype)", ("fn",)),
        metrics.counter(
            "veles_jit_calls_total",
            "calls into tracked jitted entry points", ("fn",)),
        metrics.histogram(
            "veles_jit_compile_seconds",
            "wall time of calls that triggered an XLA compilation "
            "(trace + compile + first dispatch)", ("fn",)),
        metrics.gauge(
            "veles_jit_first_compile_seconds",
            "wall time of the FIRST compiling call per entry point",
            ("fn",)),
    )


class _TrackedJit:
    """Callable proxy over a jitted function counting compiles.

    Transparent: attribute access (``_cache_size``, ``lower``,
    ``clear_cache``...) delegates to the wrapped callable."""

    def __init__(self, name, fn):
        self.name = name
        self.fn = fn
        functools.update_wrapper(self, fn, updated=())
        compiles, calls, hist, first = _compile_metrics()
        self._compiles = compiles.labels(name)
        self._calls = calls.labels(name)
        self._hist = hist.labels(name)
        self._first = first.labels(name)
        self._seen_compile = False

    def _cache_len(self):
        probe = getattr(self.fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        before = self._cache_len()
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        self._calls.inc()
        if before is not None:
            after = self._cache_len()
            if after is not None and after > before:
                dt = time.perf_counter() - t0
                self._compiles.inc(after - before)
                self._hist.observe(dt)
                if not self._seen_compile:
                    self._seen_compile = True
                    self._first.set(dt)
                events.record("jit.compile", "single", fn=self.name,
                              duration=dt)
        return out

    def __getattr__(self, name):
        return getattr(self.fn, name)


def track_jit(name, fn):
    """Wrap a jitted callable so its compiles are counted under
    ``name``.  Same-name wrappers share the metric series (an LRU
    cache re-jitting a cleared entry keeps accumulating into one
    series).  The wrapper holds no global reference: its lifetime is
    the wrapped callable's, so dropping the jit handle still frees
    the compiled executables and everything their closures pin."""
    return _TrackedJit(name, fn)


def compile_summary():
    """Per-entry-point compile digest — ``{name: {compiles, calls,
    first_compile_s, compile_seconds_total}}`` plus a ``total`` rollup;
    what ``bench.py`` records next to throughput."""
    out = {}
    total_compiles = 0
    total_seconds = 0.0
    fam_compiles = metrics.get("veles_jit_compiles_total")
    fam_calls = metrics.get("veles_jit_calls_total")
    fam_hist = metrics.get("veles_jit_compile_seconds")
    fam_first = metrics.get("veles_jit_first_compile_seconds")
    if fam_compiles is None:
        return {"total": {"compiles": 0, "compile_seconds": 0.0}}
    for (name,), child in sorted(fam_compiles.children().items()):
        compiles = int(child.value)
        hist = fam_hist.labels(name)
        calls = fam_calls.labels(name)
        first = fam_first.labels(name)
        total_compiles += compiles
        total_seconds += hist.sum
        out[name] = {
            "compiles": compiles,
            "calls": int(calls.value),
            "first_compile_s": round(first.value, 4),
            "compile_seconds_total": round(hist.sum, 4),
        }
    out["total"] = {"compiles": total_compiles,
                    "compile_seconds": round(total_seconds, 4)}
    return out


@contextlib.contextmanager
def maybe_profiler_trace():
    """When ``root.common.trace.profiler_dir`` names a directory, run
    the block under ``jax.profiler.trace`` (device-side timeline for
    TensorBoard/Perfetto); otherwise a no-op."""
    from veles_tpu.config import root
    trace_dir = root.common.trace.get("profiler_dir")
    if not trace_dir:
        yield
        return
    import jax.profiler
    with jax.profiler.trace(str(trace_dir)):
        yield
