"""JIT compile tracking — make XLA (re)compilation a first-class
metric.

Every jitted entry point in the framework (fused workflow segments,
trainer steps, serving prefill / slot decode, the ``generate()``
decode family) is wrapped with :func:`track_jit`; the wrapper detects
compilations by watching the jitted callable's executable-cache size
grow across a call (``jax.jit`` exposes ``_cache_size()``), so

- first-call compile time per entry point becomes a gauge,
- recompile counts (new shapes / dtypes hitting the same entry point)
  become a counter — the "why is the server stalling" answer that raw
  wall timers can't give,
- each detected compile also lands in the span log as a
  ``jit.compile`` event, so Chrome traces show compile gaps inline.

:func:`maybe_profiler_trace` is the opt-in ``jax.profiler`` toggle:
set ``root.common.trace.profiler_dir`` and every ``Workflow.run()``
writes a TensorBoard-loadable device trace alongside the host spans.
"""

import contextlib
import functools
import threading
import time

from veles_tpu.logger import events
from veles_tpu.telemetry.registry import metrics


def _compile_metrics():
    return (
        metrics.counter(
            "veles_jit_compiles_total",
            "XLA compilations per jitted entry point (first call + "
            "every recompile on a new shape/dtype); cache=\"hit\" "
            "marks compiles satisfied by the persistent compilation "
            "cache (fast executable loads), cache=\"cold\" real "
            "XLA compiles", ("fn", "cache")),
        metrics.counter(
            "veles_jit_calls_total",
            "calls into tracked jitted entry points", ("fn",)),
        metrics.histogram(
            "veles_jit_compile_seconds",
            "wall time of calls that triggered an XLA compilation "
            "(trace + compile + first dispatch)", ("fn",)),
        metrics.gauge(
            "veles_jit_first_compile_seconds",
            "wall time of the FIRST compiling call per entry point",
            ("fn",)),
    )


# -- cost accounting (XLA cost_analysis / memory_analysis) -------------------

#: fields every cost record carries; absent backend support → None
COST_KEYS = ("flops", "bytes_accessed", "temp_bytes", "argument_bytes",
             "output_bytes", "generated_code_bytes")

_cost_lock = threading.Lock()
_cost_records = {}   # entry-point name -> {COST_KEYS: float|int|None}
_cost_captured = set()


def _cost_gauges():
    return {
        "flops": metrics.gauge(
            "veles_jit_cost_flops",
            "XLA cost_analysis flops of the first compiled executable "
            "per entry point (roofline numerator)", ("fn",)),
        "bytes_accessed": metrics.gauge(
            "veles_jit_cost_bytes_accessed",
            "XLA cost_analysis bytes accessed per executed step "
            "(HBM-roofline denominator)", ("fn",)),
        "temp_bytes": metrics.gauge(
            "veles_jit_memory_temp_bytes",
            "XLA memory_analysis peak temp allocation of the compiled "
            "executable", ("fn",)),
        "argument_bytes": metrics.gauge(
            "veles_jit_memory_argument_bytes",
            "XLA memory_analysis argument bytes of the compiled "
            "executable", ("fn",)),
        "output_bytes": metrics.gauge(
            "veles_jit_memory_output_bytes",
            "XLA memory_analysis output bytes of the compiled "
            "executable", ("fn",)),
        "generated_code_bytes": metrics.gauge(
            "veles_jit_memory_code_bytes",
            "XLA memory_analysis generated-code size of the compiled "
            "executable", ("fn",)),
    }


def _cost_enabled():
    from veles_tpu.config import root
    return bool(root.common.telemetry.get("cost_analysis", True))


def _nonneg(v):
    """cost_analysis reports -1 for 'unknown' on some backends — that
    is an absence, not a value."""
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    return v if v >= 0 else None


def _capture_cost(name, fn, args, kwargs):
    """Record cost/memory analysis for ``name``'s executable.  Uses
    the AOT ``lower().compile()`` path (the lowering is cached from
    the call that just compiled; runs ONCE per entry-point name per
    process).  Holds no reference to ``args`` beyond this frame —
    ``lower`` reads avals, not buffers, so donated inputs are fine.
    Every absence (old jax, backend without cost analysis, sharded
    lowering quirks) degrades to ``None`` fields, never an error."""
    rec = dict.fromkeys(COST_KEYS)
    compiled = None
    try:
        compiled = fn.lower(*args, **kwargs).compile()
    except Exception:
        pass
    if compiled is not None:
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if isinstance(ca, dict):
                rec["flops"] = _nonneg(ca.get("flops"))
                rec["bytes_accessed"] = _nonneg(
                    ca.get("bytes accessed"))
        except Exception:
            pass
        try:
            ma = compiled.memory_analysis()
            rec["temp_bytes"] = _nonneg(
                getattr(ma, "temp_size_in_bytes", None))
            rec["argument_bytes"] = _nonneg(
                getattr(ma, "argument_size_in_bytes", None))
            rec["output_bytes"] = _nonneg(
                getattr(ma, "output_size_in_bytes", None))
            rec["generated_code_bytes"] = _nonneg(
                getattr(ma, "generated_code_size_in_bytes", None))
        except Exception:
            pass
    gauges = _cost_gauges()
    for key, value in rec.items():
        if value is not None:
            gauges[key].labels(name).set(value)
    with _cost_lock:
        _cost_records[name] = rec
    return rec


def cost_summary():
    """Per-entry-point cost digest — ``{name: {flops, bytes_accessed,
    temp_bytes, argument_bytes, output_bytes, generated_code_bytes}}``
    with explicit ``None`` for anything the backend couldn't report.
    bench.py records it next to throughput as the roofline
    denominator."""
    with _cost_lock:
        return {name: dict(rec) for name, rec in _cost_records.items()}


# -- persistent-compilation-cache hit detection ------------------------------
#
# jax reports persistent-cache hits through jax.monitoring
# ("/jax/compilation_cache/cache_hits"); one process-wide listener
# keeps a running count and _TrackedJit diffs it around each call to
# label the detected compile "hit" (fast executable load from
# jax_compilation_cache_dir) vs "cold" (a real XLA compile).

_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_hits_lock = threading.Lock()
_cache_hits = 0
_listener_installed = False


def _persistent_cache_hits():
    with _hits_lock:
        return _cache_hits


def _install_cache_listener():
    global _listener_installed
    with _hits_lock:
        if _listener_installed:
            return
        _listener_installed = True
    try:
        import jax

        def _on_event(event, **kwargs):
            global _cache_hits
            if event == _CACHE_HIT_EVENT:
                with _hits_lock:
                    _cache_hits += 1

        jax.monitoring.register_event_listener(_on_event)
    except Exception:  # pragma: no cover - jax without monitoring
        pass


class _TrackedJit:
    """Callable proxy over a jitted function counting compiles.

    Transparent: attribute access (``_cache_size``, ``lower``,
    ``clear_cache``...) delegates to the wrapped callable."""

    def __init__(self, name, fn):
        self.name = name
        self.fn = fn
        functools.update_wrapper(self, fn, updated=())
        compiles, calls, hist, first = _compile_metrics()
        self._compiles_family = compiles
        self._calls = calls.labels(name)
        self._hist = hist.labels(name)
        self._first = first.labels(name)
        self._seen_compile = False
        _install_cache_listener()

    def _cache_len(self):
        probe = getattr(self.fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        before = self._cache_len()
        hits_before = _persistent_cache_hits()
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        self._calls.inc()
        if before is not None:
            after = self._cache_len()
            if after is not None and after > before:
                dt = time.perf_counter() - t0
                kind = "hit" \
                    if _persistent_cache_hits() > hits_before \
                    else "cold"
                self._compiles_family.labels(self.name, kind).inc(
                    after - before)
                self._hist.observe(dt)
                with _cost_lock:  # first-compile latch: one winner
                    first_compile = not self._seen_compile
                    self._seen_compile = True
                if first_compile:
                    self._first.set(dt)
                events.record("jit.compile", "single", fn=self.name,
                              duration=dt)
                # cost/memory accounting once per entry-point NAME per
                # process (same-name rebuilds share the record): pay
                # the one AOT recompile only for the first executable.
                # Claim the name under the lock — two threads racing
                # here would each pay the AOT compile — but release it
                # before the slow _capture_cost (which re-takes it to
                # store the record).
                if _cost_enabled():
                    with _cost_lock:
                        first = self.name not in _cost_captured
                        if first:
                            _cost_captured.add(self.name)
                    if first:
                        _capture_cost(self.name, self.fn, args, kwargs)
        return out

    def __getattr__(self, name):
        return getattr(self.fn, name)


def track_jit(name, fn):
    """Wrap a jitted callable so its compiles are counted under
    ``name``.  Same-name wrappers share the metric series (an LRU
    cache re-jitting a cleared entry keeps accumulating into one
    series).  The wrapper holds no global reference: its lifetime is
    the wrapped callable's, so dropping the jit handle still frees
    the compiled executables and everything their closures pin."""
    return _TrackedJit(name, fn)


def compile_summary():
    """Per-entry-point compile digest — ``{name: {compiles,
    compiles_persistent_hit, calls, first_compile_s,
    compile_seconds_total}}`` plus a ``total`` rollup; what
    ``bench.py`` records next to throughput.  ``compiles`` counts
    every executable materialization; ``compiles_persistent_hit`` the
    subset served by the on-disk compilation cache (cheap loads, not
    real XLA compiles)."""
    out = {}
    total_compiles = 0
    total_hits = 0
    total_seconds = 0.0
    fam_compiles = metrics.get("veles_jit_compiles_total")
    fam_calls = metrics.get("veles_jit_calls_total")
    fam_hist = metrics.get("veles_jit_compile_seconds")
    fam_first = metrics.get("veles_jit_first_compile_seconds")
    if fam_compiles is None:
        return {"total": {"compiles": 0, "compile_seconds": 0.0}}
    per_fn = {}
    for (name, kind), child in fam_compiles.children().items():
        agg = per_fn.setdefault(name, {"cold": 0, "hit": 0})
        agg[kind] = agg.get(kind, 0) + int(child.value)
    for name, agg in sorted(per_fn.items()):
        compiles = agg["cold"] + agg["hit"]
        hist = fam_hist.labels(name)
        calls = fam_calls.labels(name)
        first = fam_first.labels(name)
        total_compiles += compiles
        total_hits += agg["hit"]
        total_seconds += hist.sum
        out[name] = {
            "compiles": compiles,
            "compiles_persistent_hit": agg["hit"],
            "calls": int(calls.value),
            "first_compile_s": round(first.value, 4),
            "compile_seconds_total": round(hist.sum, 4),
        }
    out["total"] = {"compiles": total_compiles,
                    "compiles_persistent_hit": total_hits,
                    "compile_seconds": round(total_seconds, 4)}
    return out


@contextlib.contextmanager
def maybe_profiler_trace():
    """When ``root.common.trace.profiler_dir`` names a directory, run
    the block under ``jax.profiler.trace`` (device-side timeline for
    TensorBoard/Perfetto); otherwise a no-op."""
    from veles_tpu.config import root
    trace_dir = root.common.trace.get("profiler_dir")
    if not trace_dir:
        yield
        return
    import jax.profiler
    with jax.profiler.trace(str(trace_dir)):
        yield
