"""Process-wide metrics registry — counters, gauges, histograms.

The reference framework pushed every record to MongoDB and aggregated
there (ref: veles/logger.py:292-332); a serving process can't afford a
database round-trip per sample, so metrics aggregate IN PROCESS behind
one lock-per-metric and export on demand:

- :class:`Counter` — monotonically increasing totals;
- :class:`Gauge` — instantaneous values (queue depth, active slots);
- :class:`Histogram` — fixed cumulative buckets (Prometheus
  exposition) plus a bounded reservoir of recent observations for
  nearest-rank percentiles (p50/p95/p99 without unbounded memory);
- labeled series: a family created with ``labelnames`` hands out one
  child per label-value tuple via :meth:`_Family.labels`.

``MetricsRegistry.render_prometheus()`` produces the text exposition
format v0.0.4 that both ``web_status.py`` and ``restful_api.py`` serve
at ``GET /metrics``.  The module-global :data:`metrics` registry is
the process-wide default — analogous to :data:`veles_tpu.logger.events`
for spans.
"""

import math
import threading
from collections import deque

#: default latency buckets (seconds): 1 ms .. 60 s, roughly log-spaced
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: millisecond-scale buckets for latency series recorded in ms (TTFT,
#: queue wait) — same spread, ms units
MS_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
              1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0)


def nearest_rank(sorted_vals, q):
    """Nearest-rank percentile over a SORTED sequence: the value at
    1-based rank ``ceil(q * n)``, clamped to the window.  ``q=0.5``
    over a 2-element window returns the LOWER value; ``q=0.99`` can
    never index out of range on tiny windows."""
    n = len(sorted_vals)
    if not n:
        return None
    i = max(0, min(n - 1, int(math.ceil(q * n)) - 1))
    return sorted_vals[i]


def _format_value(v):
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if isinstance(v, float) and v != v:
        return "NaN"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _escape_label(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _label_str(labelnames, labelvalues):
    if not labelnames:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, _escape_label(v))
        for k, v in zip(labelnames, labelvalues))


class Counter:
    """Monotonically increasing total."""

    TYPE = "counter"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount=1):
        if amount < 0:
            raise ValueError("counters only go up (inc %r)" % amount)
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value

    def samples(self):
        """Structured samples: (suffix, extra labels, value) — the
        in-process read federation/dashboard/alerts consume without
        round-tripping through the text format."""
        return [("", {}, self.value)]

    def expose(self, labels=""):
        yield "%s%s %s" % (self.name, labels,
                           _format_value(self.value))


class Gauge:
    """Instantaneous value (settable both ways)."""

    TYPE = "gauge"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = None

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        with self._lock:
            self._value -= amount

    def set_function(self, fn):
        """Read the gauge from a callback at exposition time (for
        values someone else already tracks, e.g. queue depth)."""
        self._fn = fn

    @property
    def value(self):
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        with self._lock:
            return self._value

    def samples(self):
        return [("", {}, self.value)]

    def expose(self, labels=""):
        yield "%s%s %s" % (self.name, labels,
                           _format_value(self.value))


class Histogram:
    """Cumulative fixed buckets + a bounded reservoir of recent
    observations.

    The buckets feed the Prometheus exposition (``_bucket{le=...}`` /
    ``_sum`` / ``_count``); the reservoir — a deque of the last
    ``reservoir`` observations — answers :meth:`percentile` queries by
    nearest rank, which is what serving snapshots and
    ``Workflow.print_stats`` read."""

    TYPE = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS,
                 reservoir=512):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._lock = threading.Lock()
        self._bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._recent = deque(maxlen=int(reservoir))

    def observe(self, value):
        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            self._min = value if self._min is None \
                else min(self._min, value)
            self._max = value if self._max is None \
                else max(self._max, value)
            self._recent.append(value)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._bucket_counts[i] += 1
                    break
            else:
                self._bucket_counts[-1] += 1

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    @property
    def min(self):
        with self._lock:
            return self._min

    @property
    def max(self):
        with self._lock:
            return self._max

    def mean(self):
        with self._lock:
            return self._sum / self._count if self._count else None

    def percentile(self, q):
        """Nearest-rank percentile over the recent reservoir (None on
        an empty histogram)."""
        with self._lock:
            window = sorted(self._recent)
        return nearest_rank(window, q)

    def summary(self):
        """Plain-dict digest (count/sum/mean/min/max/p50/p95/p99) —
        what bench.py and print_stats consume."""
        with self._lock:
            window = sorted(self._recent)
            count, total = self._count, self._sum
            vmin, vmax = self._min, self._max
        return {
            "count": count,
            "sum": round(total, 6),
            "mean": round(total / count, 6) if count else None,
            "min": round(vmin, 6) if vmin is not None else None,
            "max": round(vmax, 6) if vmax is not None else None,
            "p50": nearest_rank(window, 0.50),
            "p95": nearest_rank(window, 0.95),
            "p99": nearest_rank(window, 0.99),
        }

    def samples(self):
        """Structured exposition samples, cumulative buckets included
        (``le`` rides as an extra label, mirroring the text form)."""
        with self._lock:
            counts = list(self._bucket_counts)
            count, total = self._count, self._sum
        out = []
        acc = 0
        for b, c in zip(self.buckets, counts):
            acc += c
            out.append(("_bucket", {"le": _format_value(b)}, acc))
        out.append(("_bucket", {"le": "+Inf"}, acc + counts[-1]))
        out.append(("_sum", {}, total))
        out.append(("_count", {}, count))
        return out

    def expose(self, labels=""):
        with self._lock:
            counts = list(self._bucket_counts)
            count, total = self._count, self._sum
        # exposition buckets are CUMULATIVE
        acc = 0
        inner = labels[1:-1] if labels else ""
        for b, c in zip(self.buckets, counts):
            acc += c
            sep = "," if inner else ""
            yield '%s_bucket{%s%sle="%s"} %d' % (
                self.name, inner, sep, _format_value(b), acc)
        acc += counts[-1]
        sep = "," if inner else ""
        yield '%s_bucket{%s%sle="+Inf"} %d' % (self.name, inner, sep,
                                               acc)
        yield "%s_sum%s %s" % (self.name, labels, _format_value(total))
        yield "%s_count%s %d" % (self.name, labels, count)


class _Family:
    """A labeled metric family: one child metric per label-value
    tuple, created on first use."""

    def __init__(self, cls, name, help, labelnames, **kwargs):
        self.cls = cls
        self.name = name
        self.help = help
        self.TYPE = cls.TYPE
        self.labelnames = tuple(labelnames)
        self._kwargs = kwargs
        self._lock = threading.Lock()
        self._children = {}

    def labels(self, *labelvalues, **labelkv):
        if labelkv:
            if labelvalues:
                raise ValueError(
                    "pass label values positionally OR by name")
            labelvalues = tuple(labelkv[k] for k in self.labelnames)
        labelvalues = tuple(str(v) for v in labelvalues)
        if len(labelvalues) != len(self.labelnames):
            raise ValueError("expected labels %s, got %r"
                             % (self.labelnames, labelvalues))
        with self._lock:
            child = self._children.get(labelvalues)
            if child is None:
                child = self.cls(self.name, self.help, **self._kwargs)
                self._children[labelvalues] = child
        return child

    def children(self):
        with self._lock:
            return dict(self._children)

    def remove(self, *labelvalues):
        """Drop one child series (e.g. a deregistered replica's
        labeled gauge) so stale labels stop exporting forever."""
        labelvalues = tuple(str(v) for v in labelvalues)
        with self._lock:
            self._children.pop(labelvalues, None)

    def samples(self):
        out = []
        for labelvalues, child in sorted(self.children().items()):
            base = dict(zip(self.labelnames, labelvalues))
            for suffix, extra, value in child.samples():
                labels = dict(base)
                labels.update(extra)
                out.append((suffix, labels, value))
        return out

    def expose(self):
        for labelvalues, child in sorted(self.children().items()):
            for line in child.expose(
                    _label_str(self.labelnames, labelvalues)):
                yield line


class MetricsRegistry:
    """Get-or-create registry of metric families.

    ``counter/gauge/histogram`` return the existing series when the
    name is already registered (same semantics as ``logging.getLogger``
    — modules declare the metrics they touch without coordinating);
    asking for a registered name with a different type raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}   # name -> metric or _Family

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.TYPE != cls.TYPE:
                    raise ValueError(
                        "metric %s already registered as %s"
                        % (name, existing.TYPE))
                return existing
            if labelnames:
                m = _Family(cls, name, help, labelnames, **kwargs)
            else:
                m = cls(name, help, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS, reservoir=512):
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets, reservoir=reservoir)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def collect(self):
        with self._lock:
            return sorted(self._metrics.items())

    def collect_families(self):
        """Structured exposition: one dict per family —
        ``{name, type, help, samples: [(suffix, labels, value)]}`` —
        the in-process read the fleet federation merger, the alert
        engine and the dashboards consume directly, instead of
        rendering to the text format and parsing it back."""
        out = []
        for name, m in self.collect():
            out.append({"name": name, "type": m.TYPE,
                        "help": m.help, "samples": m.samples()})
        return out

    def render_prometheus(self):
        """The registry as Prometheus text exposition format v0.0.4
        (the one text renderer, over :meth:`collect_families`)."""
        return render_families_text(self.collect_families())

    def snapshot(self):
        """Plain nested dict of every series (histograms as their
        :meth:`Histogram.summary`) — the JSON-friendly read used by
        bench.py and status payloads."""
        out = {}
        for name, m in self.collect():
            if isinstance(m, _Family):
                fam = {}
                for lv, child in sorted(m.children().items()):
                    key = ",".join(lv)
                    fam[key] = child.summary() \
                        if isinstance(child, Histogram) else child.value
                out[name] = fam
            elif isinstance(m, Histogram):
                out[name] = m.summary()
            else:
                out[name] = m.value
        return out


def render_families_text(families):
    """Render structured families (the :meth:`MetricsRegistry.
    collect_families` / federation-merge shape) as Prometheus text
    exposition v0.0.4 — the single text renderer behind every
    ``GET /metrics`` surface and the router's ``/metrics/fleet``."""
    lines = []
    for fam in families:
        name = fam["name"]
        if fam.get("help"):
            lines.append("# HELP %s %s" % (
                name, fam["help"].replace("\\", "\\\\")
                .replace("\n", "\\n")))
        lines.append("# TYPE %s %s" % (name, fam["type"]))
        for suffix, labels, value in fam["samples"]:
            label_str = _label_str(tuple(labels), tuple(
                labels.values())) if labels else ""
            if suffix in ("_bucket", "_count"):
                lines.append("%s%s%s %d" % (name, suffix, label_str,
                                            value))
            else:
                lines.append("%s%s%s %s" % (name, suffix, label_str,
                                            _format_value(value)))
    return "\n".join(lines) + "\n"


#: the process-wide registry (the ``GET /metrics`` surface)
metrics = MetricsRegistry()
