"""The live serving dashboard — one auto-refreshing escaped-HTML
page over the fleet's state.

Veles shipped a web-status server as a first-class platform component
(``web_status.py`` rebuilds it for training runs); this module is the
*serving* counterpart: :func:`render_dashboard_html` turns the data
the router/replica tiers already hold — the replica table, SLO burn
rates, firing alerts, the live in-flight request table and the
goodput/padding gauges — into a single operator page, served at
``GET /dashboard`` on the router and on ``web_status``.

Discipline inherited from ``web_status.py``: EVERY interpolated
string is attacker input (replica ids come off the wire, trace ids
from clients) and goes through ``html.escape`` — the page must render
a hostile replica id as text, never as markup.
"""

import html
import time

_PAGE = """<!DOCTYPE html>
<html><head><title>%TITLE%</title>
<meta http-equiv="refresh" content="%REFRESH%">
<style>
 body { font-family: sans-serif; margin: 2em; }
 table { border-collapse: collapse; margin-bottom: 1.2em; }
 td, th { border: 1px solid #999; padding: 4px 10px; }
 th { background: #eee; }
 h3 { margin-bottom: 0.3em; }
 .page { color: #fff; background: #c0392b; }
 .ticket { color: #fff; background: #d68910; }
 .info { background: #d6eaf8; }
 .bad { color: #c0392b; font-weight: bold; }
 .warn { color: #d68910; }
 .meter { display: inline-block; height: 10px; background: #2e86c1;
          vertical-align: middle; }
 .dim { color: #888; }
</style></head>
<body><h2>%TITLE%</h2>%BODY%
<p class="dim">rendered %NOW% &middot; auto-refresh %REFRESH%s
 &middot; <a href="/alerts">alerts</a>
 <a href="/metrics">metrics</a></p></body></html>
"""


def _e(v, dash="-"):
    return html.escape(str(v)) if v is not None else dash


def _num(v, fmt="%.3g", dash="-"):
    try:
        return fmt % float(v)
    except (TypeError, ValueError):
        return dash


def _table(headers, rows):
    head = "".join("<th>%s</th>" % html.escape(h) for h in headers)
    body = "".join(
        "<tr>%s</tr>" % "".join("<td>%s</td>" % c for c in row)
        for row in rows)
    return "<table><tr>%s</tr>%s</table>" % (head, body)


def render_fleet_table(replicas):
    """The fleet table: one row per replica view dict (the router's
    ``_Replica.view()`` shape, ``last metrics`` fields included)."""
    if not replicas:
        return "<p class='dim'>no replicas registered</p>"
    rows = []
    for r in replicas:
        breaker = _e(r.get("breaker"))
        if r.get("breaker") == "open":
            breaker = "<span class='bad'>%s</span>" % breaker
        status = _e(r.get("status"))
        if r.get("status") not in ("ok", None):
            status = "<span class='warn'>%s</span>" % status
        rows.append((
            _e(r.get("id")), _e(r.get("role")), _e(r.get("tp")),
            status, breaker, _e(r.get("outstanding")),
            _e(r.get("queue_depth")),
            "%s/%s" % (_e(r.get("kv_blocks_used")),
                       _e(r.get("kv_blocks_free"))),
            _num(r.get("prefix_hit_rate")),
            _num(r.get("spec_accept_rate")),
            _num(r.get("goodput_tokens_per_sec"), "%.1f"),
            _num(r.get("bucket_padding_efficiency"), "%.2f"),
        ))
    return _table(("replica", "role", "tp", "status", "breaker",
                   "outstanding", "queue", "kv used/free",
                   "prefix hit", "spec accept", "goodput tok/s",
                   "pad eff"), rows)


def render_slo_meters(slo):
    """Burn-rate meters from an ``SLOTracker.snapshot()`` dict: one
    row per (class, kind), a bar per window (width saturates at
    14.4x — the page threshold)."""
    classes = (slo or {}).get("classes") or {}
    if not classes:
        return "<p class='dim'>no SLO observations yet</p>"
    rows = []
    for cls in sorted(classes):
        for kind in sorted(classes[cls]):
            rec = classes[cls][kind]
            burns = rec.get("burn_rate") or {}
            cells = [_e(cls), _e(kind),
                     "%s/%s" % (_e(rec.get("good", 0)),
                                _e(rec.get("bad", 0)))]
            for w in sorted(burns, key=lambda s: int(s.rstrip("s"))):
                burn = burns[w]
                width = max(1, min(100, int(
                    100 * float(burn or 0) / 14.4)))
                klass = " bad" if (burn or 0) >= 14.4 \
                    else (" warn" if (burn or 0) >= 1 else "")
                cells.append(
                    "%s: <span class='meter%s' style='width:%dpx'>"
                    "</span> %s" % (_e(w), klass, width, _num(burn)))
            rows.append(cells)
    width = max(len(r) for r in rows)
    rows = [tuple(r) + ("-",) * (width - len(r)) for r in rows]
    headers = ("class", "slo", "good/bad") \
        + tuple("burn" for _ in range(width - 3))
    return _table(headers, rows)


def render_alerts_table(firing, pending=()):
    if not firing and not pending:
        return "<p class='dim'>no alerts firing</p>"
    rows = []
    for state, alerts in (("firing", firing), ("pending", pending)):
        for a in alerts:
            sev = _e(a.get("severity"))
            rows.append((
                "<span class='%s'>%s</span>" % (sev, sev),
                _e(a.get("rule")), _e(state),
                _e(", ".join("%s=%s" % kv for kv in sorted(
                    (a.get("labels") or {}).items()))),
                _num(a.get("value")),
                _num(a.get("firing_for_s"), "%.1f")))
    return _table(("severity", "rule", "state", "labels", "value",
                   "for (s)"), rows)


def render_inflight_table(requests):
    if not requests:
        return "<p class='dim'>no requests in flight</p>"
    rows = [(
        _e(r.get("trace")), _e(r.get("phase")), _e(r.get("path")),
        _e(r.get("cls")), _num(r.get("age_s"), "%.2f"),
        _e(r.get("attempts")), _e(r.get("replica")),
        "yes" if r.get("stream") else "no",
    ) for r in requests]
    return _table(("trace", "phase", "path", "class", "age (s)",
                   "attempts", "replica", "stream"), rows)


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(points, width=40):
    """A unicode block sparkline over ``[(t, value)]`` points (last
    ``width`` kept) — no javascript, no external assets, survives
    any terminal-grade browser.  Returns "" for no data."""
    vals = [float(v) for _, v in points][-int(width):]
    vals = [v for v in vals if v == v]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_BLOCKS[0] * len(vals)
    return "".join(
        _SPARK_BLOCKS[min(len(_SPARK_BLOCKS) - 1,
                          int((v - lo) / span * len(_SPARK_BLOCKS)))]
        for v in vals)


def render_history_sparklines(history):
    """The history section: ``history`` maps display name ->
    ``[(t, value)]`` tier-0 points (the tsdb ``points()`` shape).
    One row per series: sparkline + last/min/max over the window."""
    if not history:
        return "<p class='dim'>no history yet</p>"
    rows = []
    for name in sorted(history):
        points = list(history[name] or ())
        vals = [float(v) for _, v in points if v == v]
        if not vals:
            continue
        rows.append((
            _e(name),
            "<span style='font-family:monospace'>%s</span>"
            % html.escape(_sparkline(points)),
            _num(vals[-1]), _num(min(vals)), _num(max(vals))))
    if not rows:
        return "<p class='dim'>no history yet</p>"
    return _table(("series", "trend", "last", "min", "max"), rows)


def render_tenant_usage(usage):
    """The per-tenant metering lines: ``usage`` is the router's
    ``/tenants/usage`` payload (``{"window_s", "tenants": {label:
    {...}}}``)."""
    tenants = (usage or {}).get("tenants") or {}
    if not tenants:
        return "<p class='dim'>no tenant usage recorded</p>"
    rows = []
    for tenant in sorted(tenants):
        rec = tenants[tenant]
        rows.append((
            _e(tenant),
            _e(rec.get("prompt_tokens")),
            _e(rec.get("generated_tokens")),
            _num(rec.get("generated_tokens_per_sec")),
            _num(rec.get("kv_block_seconds"), "%.2f"),
            _num(rec.get("compute_seconds"), "%.3f")))
    return _table(("tenant", "prompt tok", "generated tok",
                   "gen tok/s", "kv block-s", "compute-s"), rows)


def render_dashboard_html(title, replicas=(), slo=None, alerts=None,
                          inflight=(), note=None, refresh=2,
                          history=None, tenants=None):
    """Compose the full page.  ``alerts`` is an
    ``AlertEngine.snapshot()`` dict (or None); ``history`` maps
    series display names to tier-0 point lists (sparkline rows);
    ``tenants`` is the ``/tenants/usage`` payload."""
    alerts = alerts or {}
    parts = []
    if note:
        parts.append("<p>%s</p>" % html.escape(str(note)))
    parts.append("<h3>fleet</h3>")
    parts.append(render_fleet_table(list(replicas)))
    parts.append("<h3>SLO burn</h3>")
    parts.append(render_slo_meters(slo))
    parts.append("<h3>alerts</h3>")
    parts.append(render_alerts_table(
        alerts.get("firing") or (), alerts.get("pending") or ()))
    if history is not None:
        parts.append("<h3>history</h3>")
        parts.append(render_history_sparklines(history))
    if tenants is not None:
        parts.append("<h3>tenant usage</h3>")
        parts.append(render_tenant_usage(tenants))
    parts.append("<h3>in flight</h3>")
    parts.append(render_inflight_table(list(inflight)))
    return (_PAGE
            .replace("%REFRESH%", str(int(refresh)))
            .replace("%TITLE%", html.escape(str(title)))
            .replace("%NOW%", time.strftime("%H:%M:%S"))
            .replace("%BODY%", "".join(parts)))
