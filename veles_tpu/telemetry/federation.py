"""Fleet metrics federation — merge N replica scrapes into one view.

Every serving replica already exports the process-wide registry as
Prometheus text at ``GET /metrics``, but a fleet of N replicas means
N scrapes an operator has to diff by hand.  This module is the rollup
tier (the Prometheus-federation analogue, in-process): the router's
health-poll task stores each replica's latest ``/metrics`` text, and
``GET /metrics/fleet`` serves the merge —

- **counters** sum across replicas per label set (cumulative bucket
  counts of histograms sum the same way, so ``_bucket``/``_sum``/
  ``_count`` merge without un-cumulating);
- **gauges** are instantaneous per-process facts (queue depth, KV
  blocks free) — summing them would lie, so every gauge series is
  re-labeled with ``replica="<id>"`` and kept per replica;
- ``veles_fleet_*`` rollup families ride along: replica/scrape
  counts and a per-replica ``up`` gauge, so "how many replicas did
  this merge actually see" is part of the scrape itself.

Scrape payloads are either raw exposition text (the wire path,
:func:`parse_prometheus`) or the structured family list
:meth:`~veles_tpu.telemetry.registry.MetricsRegistry.collect_families`
returns (the in-process path — dashboard and alert consumers never
round-trip through text).
"""

import re

from veles_tpu.telemetry.registry import render_families_text

__all__ = ("parse_prometheus", "merge_scrapes", "fleet_families",
           "render_families_text")

#: one exposition sample: name, optional {labels}, value
_SAMPLE = re.compile(
    r'^([A-Za-z_:][A-Za-z0-9_:]*)\s*(?:\{(.*)\})?\s+(\S+)$')
_LABEL = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SUFFIXES = ("_bucket", "_sum", "_count")


def _unescape(v):
    return v.replace("\\n", "\n").replace('\\"', '"') \
        .replace("\\\\", "\\")


def _parse_value(raw):
    if raw == "+Inf":
        return float("inf")
    if raw == "-Inf":
        return float("-inf")
    return float(raw)


def parse_prometheus(text):
    """Parse exposition text v0.0.4 into the structured family list
    (same shape as ``MetricsRegistry.collect_families()``).  Unknown
    lines are skipped — a scrape is operator input, not a trusted
    peer, and a malformed line must cost one family at most."""
    families = {}   # name -> family dict
    types = {}      # name -> type
    helps = {}

    def family(name):
        fam = families.get(name)
        if fam is None:
            fam = families[name] = {
                "name": name, "type": types.get(name, "untyped"),
                "help": helps.get(name, ""), "samples": []}
        return fam

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                types[parts[2]] = parts[3] if len(parts) > 3 \
                    else "untyped"
            elif len(parts) >= 3 and parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE.match(line)
        if m is None:
            continue
        name, labelblob, raw = m.groups()
        try:
            value = _parse_value(raw)
        except ValueError:
            continue
        labels = {k: _unescape(v)
                  for k, v in _LABEL.findall(labelblob or "")}
        base, suffix = name, ""
        for s in _SUFFIXES:
            if name.endswith(s) and types.get(name[:-len(s)]) \
                    in ("histogram", "summary"):
                base, suffix = name[:-len(s)], s
                break
        family(base)["samples"].append((suffix, labels, value))
    return sorted(families.values(), key=lambda f: f["name"])


def _labels_key(labels):
    return tuple(sorted(labels.items()))


def merge_scrapes(scrapes):
    """Merge per-replica scrapes into one family list.

    ``scrapes`` is ``[(replica_id, families), ...]`` where each
    ``families`` is a parsed/collected family list.  Counter and
    histogram samples SUM across replicas per label set (cumulative
    bucket counts sum to cumulative counts, so histogram merge needs
    no un-cumulating); gauge samples are per-process facts and are
    kept per replica, re-labeled with ``replica="<id>"``."""
    merged = {}     # name -> {"type","help","samples": {key: [s,labels,v]}}
    for replica, families in scrapes:
        for fam in families:
            name = fam["name"]
            rec = merged.get(name)
            if rec is None:
                rec = merged[name] = {"type": fam["type"],
                                      "help": fam.get("help", ""),
                                      "samples": {}}
            summing = rec["type"] in ("counter", "histogram")
            for suffix, labels, value in fam["samples"]:
                labels = dict(labels)
                if not summing:
                    # re-label by scrape origin; a gauge already
                    # carrying a finer replica label keeps it
                    labels.setdefault("replica", str(replica))
                key = (suffix, _labels_key(labels))
                slot = rec["samples"].get(key)
                if slot is None:
                    rec["samples"][key] = [suffix, labels, value]
                elif summing:
                    slot[2] += value
                else:   # duplicate gauge series from one replica:
                    slot[2] = value       # last write wins, like prom
    def _sample_key(kv):
        suffix, labels_key = kv[0]
        ordered = []
        for k, v in labels_key:
            if k == "le":   # buckets sort numerically, +Inf last
                try:
                    v = (float("inf"), "") if v == "+Inf" \
                        else (float(v), "")
                except ValueError:
                    v = (float("inf"), v)
            else:
                v = (0.0, v)
            ordered.append((k, v))
        return (suffix, ordered)

    out = []
    for name in sorted(merged):
        rec = merged[name]
        samples = [tuple(s) for _, s in sorted(
            rec["samples"].items(), key=_sample_key)]
        out.append({"name": name, "type": rec["type"],
                    "help": rec["help"], "samples": samples})
    return out


def fleet_families(scrapes, errors=()):
    """The full ``GET /metrics/fleet`` payload: the merged replica
    families plus the ``veles_fleet_*`` rollups.  ``errors`` names
    the replicas whose scrape failed this cycle (they export
    ``up=0`` and count into ``veles_fleet_scrape_errors``)."""
    families = merge_scrapes(scrapes)
    up = [("", {"replica": str(r)}, 1.0) for r, _ in scrapes]
    up += [("", {"replica": str(r)}, 0.0) for r in errors]
    rollups = [
        {"name": "veles_fleet_replicas", "type": "gauge",
         "help": "replicas merged into this fleet scrape",
         "samples": [("", {}, float(len(scrapes)))]},
        {"name": "veles_fleet_scrape_errors", "type": "gauge",
         "help": "replicas whose /metrics scrape failed this cycle",
         "samples": [("", {}, float(len(errors)))]},
        {"name": "veles_fleet_up", "type": "gauge",
         "help": "1 per replica whose scrape merged, 0 when its "
                 "last scrape failed",
         "samples": sorted(up, key=lambda s: s[1]["replica"])},
    ]
    return sorted(families + rollups, key=lambda f: f["name"])
