"""Per-request distributed tracing for the serving fleet.

PAPER.md's blueprint centers on an inspectable dataflow graph — the
reference system could say what every unit was doing and why — and the
training side rebuilt that as spans + Chrome-trace export.  The
serving fleet (router retries/hedges, priority preemption, chunked
prefill, speculative verify, radix admission, SSE proxying) only
exposed *aggregate* Prometheus families; this module adds the
Dapper-style per-request axis, so "why did THIS request take 3 s at
p99" is answerable across router → replica → scheduler:

- a **trace id** is minted at the edge (router or a direct REST /
  OpenAI-facade hit) or accepted from the client via the
  ``X-Veles-Trace`` header (:data:`TRACE_HEADER`), sanitized
  (:func:`clean_trace_id` — header/JSONL material, so no whitespace
  or control bytes survive), and echoed on EVERY reply including
  structured errors and SSE terminal frames;
- the router records a ``router.request`` begin/end pair per routed
  request and a ``router.attempt`` begin/end pair per forward attempt
  (retries and hedges each get their own child span, tagged with the
  attempt number and replica id);
- the scheduler records phase spans at the boundaries it already
  owns — queue wait, admission (cold vs prefix-warm, blocks
  claimed), each prefill chunk, batched decode/verify boundaries
  (ONE ``req.step`` span per boundary carrying per-request token
  counts in its ``traces`` map — per-slot spans would multiply the
  hot-path cost by occupancy), preempt/resume, first token, retire —
  all through the existing JSONL event sink
  (:data:`veles_tpu.logger.events`), which is what lets
  ``python -m veles_tpu.telemetry.trace_export --request <id>``
  merge router + N replica logs into one parented Chrome trace;
- a process-wide **in-flight registry** (:func:`register` /
  :func:`inflight_table`) lets the flight recorder and
  ``GET /debug/requests`` enumerate live requests (trace id, phase,
  age, blocks held) without the scheduler/router importing the
  recorder.

Tracing is ON by default (``root.common.reqtrace.enabled``) with
bounded overhead: every record is one dict append to the bounded
in-memory ring (plus a JSONL line only when a file sink is open), the
per-boundary decode span amortizes over the whole batch, and the
tier-1 ``tracing_overhead`` gate holds the on-vs-off delta under 5%
(the PR 2 telemetry-overhead precedent).
"""

import os
import re
import threading
import weakref

from veles_tpu.logger import events

#: the propagation/echo header (case-insensitive on the wire)
TRACE_HEADER = "X-Veles-Trace"

#: client-supplied ids are header AND log material: strip anything
#: outside this set so a hostile header can't inject CRLF into a
#: reply or structure into the JSONL sink
_SAFE = re.compile(r"[^A-Za-z0-9._:-]")
_MAX_ID = 64


def new_trace_id():
    """A fresh 16-hex trace id (64 random bits — collision-safe at
    fleet request rates, short enough to grep by hand)."""
    return os.urandom(8).hex()


def clean_trace_id(raw):
    """Sanitize a client-supplied trace id; ``None`` when nothing
    usable survives (caller then mints a fresh one)."""
    if raw is None:
        return None
    s = _SAFE.sub("", str(raw).strip())[:_MAX_ID]
    return s or None


def ensure_trace_id(raw=None):
    """The edge mint: the sanitized client id when one was sent,
    else a fresh one."""
    return clean_trace_id(raw) or new_trace_id()


def enabled():
    """Whether request tracing emits span events
    (``root.common.reqtrace.enabled``, default True).  Trace ids are
    minted and echoed regardless — only the event emission is gated,
    so correlation headers keep working even with tracing off."""
    from veles_tpu.config import root
    return bool(root.common.reqtrace.get("enabled", True))


def record(trace, phase, sink=None, **attrs):
    """One request-phase event: ``req.<phase>`` single carrying the
    ``trace`` id (the exporter's merge key).  A ``duration`` attr (in
    seconds) renders as a backdated complete slice in the Chrome
    trace — emit at the END of the phase with the measured wall
    time."""
    if trace is None:
        return None
    return (sink or events).record("req." + phase, "single",
                                   trace=str(trace), **attrs)


def record_step(traces, sink=None, **attrs):
    """One BATCHED decode/verify boundary: ``traces`` maps each
    participating request's trace id to the tokens it emitted at this
    boundary (0 for a slot whose drafts all rejected).  One span per
    boundary keeps tracing cost independent of occupancy; the
    ``--request`` exporter projects out the one id it is following."""
    if not traces:
        return None
    return (sink or events).record("req.step", "single",
                                   traces=dict(traces), **attrs)


# -- live in-flight registry --------------------------------------------------
#
# Schedulers and routers register themselves (weakly — a closed
# scheduler must not be pinned alive by forensics plumbing); the
# flight recorder and debug surfaces read the merged table.

_providers = {}
_plock = threading.Lock()


def register(name, obj, attr="debug_requests"):
    """Register a live in-flight provider: ``obj.<attr>()`` must
    return a list of row dicts (see
    :meth:`InferenceScheduler.debug_requests`).  Held by weakref —
    dead providers drop out of :func:`inflight_table` silently."""
    with _plock:
        _providers[id(obj)] = (str(name), weakref.ref(obj), str(attr))


def inflight_table():
    """The merged live in-flight request table across every
    registered provider — what a flight-recorder bundle embeds next
    to the thread stacks, so a hang dump shows WHICH requests were
    stuck, not just where the threads stood.  Every provider guards
    itself: a dying scheduler must not break a crash dump."""
    with _plock:
        items = list(_providers.items())
    out = []
    for key, (name, ref, attr) in items:
        obj = ref()
        if obj is None:
            with _plock:
                _providers.pop(key, None)
            continue
        try:
            rows = getattr(obj, attr)()
        except Exception:
            continue
        for row in rows:
            row = dict(row)
            row.setdefault("source", name)
            out.append(row)
    return out
