"""Embedded time-series store — the observability plane's memory.

Every signal the fleet emits was instantaneous until now: the
dashboard a point-in-time snapshot, the controller deciding off the
current tick, and ROADMAP item 1's "tune from KV-pressure *history*"
blocked on the fact that no history existed anywhere.  This module is
that history: an in-process, allocation-bounded store that samples a
metrics source on a background ticker into downsampling tiers and
answers windowed queries without a database, a scrape pipeline or an
unbounded allocation.

**Tiers** (``root.common.tsdb.tiers``, default 1s x 10min /
10s x 1h / 60s x 24h): each tier is a ``(step_s, retention_s)`` pair
backed by one ring per series — a sample lands in EVERY tier's
current bucket, so a query picks the finest tier whose retention
covers its window and never re-aggregates across tiers.

**Counters are stored as deltas**, not cumulative values: each bucket
holds the increase observed inside it, so a rate over any window is
``sum(deltas) / window`` at EVERY tier — exact across tier
boundaries, and a counter reset (replica respawn) clamps to delta 0
instead of poisoning the record with a negative spike.  Dead
replicas' contributions stay in the buckets they landed in: fleet
history survives replica churn.  Gauges keep per-bucket
``(count, sum, min, max, last)`` aggregates, so avg/min/max are exact
at every tier and quantile queries over the finest tier see the raw
samples themselves.

**Bounds**: ``max_series`` caps distinct series (later arrivals are
counted in ``dropped_series``, never stored); ``max_bytes`` is the
estimated-allocation budget — when the rings outgrow it, whole
least-recently-updated series are evicted (``evicted_series``) until
the estimate fits.  Histogram ``_bucket`` samples are skipped (their
``le`` cardinality would eat the budget for no queryable gain);
``_sum``/``_count`` ride as monotone series, which is what rate
queries need.

Stores register weakly (:func:`register_store`) like alert engines,
so the flight recorder can embed :func:`bundle_history` — the last
minutes of tier-0 history for the SLO/goodput/KV-pressure series —
and ``GET /metrics/history`` on replicas and the router both answer
from :meth:`TimeSeriesStore.history`.
"""

import math
import threading
import time
from collections import deque

from veles_tpu.logger import Logger
from veles_tpu.telemetry.registry import (
    metrics as default_registry, nearest_rank)

__all__ = ("TimeSeriesStore", "DEFAULT_TIERS", "register_store",
           "live_stores", "default_store", "bundle_history",
           "history_query")

#: (step seconds, retention seconds) per downsampling tier,
#: finest first
DEFAULT_TIERS = ((1.0, 600.0), (10.0, 3600.0), (60.0, 86400.0))

#: estimated allocation per stored bucket (python floats + list +
#: deque slot) — the byte-budget unit; an estimate the eviction test
#: holds the store to, not an exact heap measurement
POINT_BYTES = 112

#: series whose tier-0 tail a flight-recorder bundle embeds (the
#: lead-up to a hang, not just the moment of death)
BUNDLE_SERIES = ("veles_serving_goodput_tokens_per_sec",
                 "veles_serving_kv_pressure",
                 "veles_slo_burn_rate",
                 "veles_serving_ttft_p95_ms")


def _tsdb_conf(name, default):
    from veles_tpu.config import root
    return root.common.tsdb.get(name, default)


class _Series:
    """One (name, label set) series: a raw-value memory for delta
    extraction plus one ring per tier."""

    __slots__ = ("name", "labels", "monotone", "last_raw", "updated",
                 "rings")

    def __init__(self, name, labels, monotone, tiers):
        self.name = name
        self.labels = labels          # tuple(sorted(items))
        self.monotone = monotone
        self.last_raw = None
        self.updated = 0.0
        self.rings = tuple(
            deque(maxlen=max(1, int(retention / step)))
            for step, retention in tiers)

    def ingest(self, value, now, tiers):
        if self.monotone:
            v = max(0.0, value - self.last_raw) \
                if self.last_raw is not None else 0.0
            self.last_raw = value
        else:
            v = value
        self.updated = now
        for ring, (step, _) in zip(self.rings, tiers):
            bucket_t = math.floor(now / step) * step
            if ring and ring[-1][0] == bucket_t:
                p = ring[-1]
                if self.monotone:
                    p[1] += v
                else:
                    p[1] += 1
                    p[2] += v
                    p[3] = min(p[3], v)
                    p[4] = max(p[4], v)
                    p[5] = v
            elif self.monotone:
                ring.append([bucket_t, v])
            else:
                ring.append([bucket_t, 1, v, v, v, v])

    def points_used(self):
        return sum(len(r) for r in self.rings)


class TimeSeriesStore(Logger):
    """Tiered ring-buffer store over one metrics source.

    ``collect`` is a zero-arg callable returning structured families
    (the :meth:`MetricsRegistry.collect_families` / federation-merge
    shape); the default samples the process-wide registry.  The
    router passes its federated-merge closure instead, which is what
    makes fleet history survive replica churn.  :meth:`start` arms a
    ticker at the finest tier's step; tests drive :meth:`sample`
    directly with explicit timestamps."""

    def __init__(self, name="tsdb", collect=None, registry=None,
                 tiers=None, max_series=None, max_bytes=None,
                 interval=None):
        super(TimeSeriesStore, self).__init__()
        self.name = str(name)
        reg = registry if registry is not None else default_registry
        self._collect = collect if collect is not None \
            else reg.collect_families
        raw = tiers if tiers is not None \
            else _tsdb_conf("tiers", DEFAULT_TIERS)
        self.tiers = tuple(sorted(
            (float(s), float(r)) for s, r in raw))
        if not self.tiers:
            raise ValueError("tsdb needs at least one tier")
        self.max_series = int(_tsdb_conf("max_series", 512)
                              if max_series is None else max_series)
        self.max_bytes = int(_tsdb_conf("max_bytes", 16 << 20)
                             if max_bytes is None else max_bytes)
        self.interval = float(self.tiers[0][0]
                              if interval is None else interval)
        self._lock = threading.Lock()
        self._series = {}       # (name, labels tuple) -> _Series
        self.samples = 0
        self.dropped_series = 0
        self.evicted_series = 0
        self._stop = threading.Event()
        self._thread = None
        register_store(self)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        with self._lock:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="tsdb-%s" % self.name)
                self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(5)

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.sample()
            except Exception as e:  # the ticker must outlive any bug
                self.warning("tsdb sample failed: %r", e)

    # -- ingest ------------------------------------------------------------

    def sample(self, now=None, families=None):
        """One sampling pass over the source (or explicit
        ``families`` — the router's loop-thread merge hands its
        result in directly)."""
        now = time.time() if now is None else now
        if families is None:
            families = self._collect()
        with self._lock:
            self.samples += 1
            for fam in families:
                kind = fam.get("type")
                base = fam["name"]
                for suffix, labels, value in fam["samples"]:
                    if suffix == "_bucket":
                        continue     # le-cardinality: not stored
                    monotone = kind == "counter" \
                        or suffix in ("_sum", "_count")
                    try:
                        v = float(value)
                    except (TypeError, ValueError):
                        continue
                    if v != v:       # NaN never lands in a ring
                        continue
                    self._ingest(base + suffix, labels, v, monotone,
                                 now)
            self._enforce_budget()

    def _ingest(self, name, labels, value, monotone, now):
        key = (name, tuple(sorted(
            (str(k), str(v)) for k, v in (labels or {}).items())))
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= self.max_series:
                self.dropped_series += 1
                return
            series = self._series[key] = _Series(
                name, key[1], monotone, self.tiers)
        series.ingest(value, now, self.tiers)

    def _enforce_budget(self):
        """lock held: evict least-recently-updated whole series until
        the allocation estimate fits the byte budget."""
        while self.bytes_used(locked=True) > self.max_bytes \
                and self._series:
            stale = min(self._series,
                        key=lambda k: self._series[k].updated)
            del self._series[stale]
            self.evicted_series += 1

    def bytes_used(self, locked=False):
        """Estimated ring allocation (POINT_BYTES per stored
        bucket)."""
        if locked:
            return sum(s.points_used()
                       for s in self._series.values()) * POINT_BYTES
        with self._lock:
            return sum(s.points_used()
                       for s in self._series.values()) * POINT_BYTES

    # -- query -------------------------------------------------------------

    def _match(self, series, labels):
        sel = {str(k): str(v) for k, v in (labels or {}).items()}
        out = []
        for (name, ltuple), s in self._series.items():
            if name != series:
                continue
            have = dict(ltuple)
            if any(have.get(k) != v for k, v in sel.items()):
                continue
            out.append(s)
        return out

    def label_sets(self, series, labels=None):
        """Distinct label dicts stored under ``series`` that match
        the selector — the alert grammar's per-series fan-out (each
        matching series keeps its own state machine)."""
        with self._lock:
            return [dict(s.labels)
                    for s in self._match(series, labels)]

    def tier_for(self, window, tier=None):
        """The finest tier index whose retention covers ``window``
        (the coarsest tier as the fallback)."""
        if tier is not None:
            return max(0, min(len(self.tiers) - 1, int(tier)))
        for i, (_, retention) in enumerate(self.tiers):
            if window <= retention:
                return i
        return len(self.tiers) - 1

    def points(self, series, labels=None, window=60.0, tier=None,
               now=None):
        """``[(bucket_t, value)]`` over the window, oldest first —
        gauge buckets contribute their last raw sample, counter
        buckets their delta.  The sparkline / history-endpoint /
        flight-recorder read."""
        now = time.time() if now is None else now
        ti = self.tier_for(float(window), tier)
        cutoff = now - float(window)
        with self._lock:
            matched = self._match(series, labels)
            rows = []
            for s in matched:
                for p in s.rings[ti]:
                    if p[0] >= cutoff:
                        rows.append((p[0], p[1] if s.monotone
                                     else p[5]))
        rows.sort()
        return rows

    def range(self, series, labels=None, window=60.0, agg="avg",
              now=None, tier=None):
        """One aggregate over the window: ``avg``/``min``/``max``/
        ``last``/``sum``, a nearest-rank quantile (``"p95"`` or a
        float in (0, 1)), ``rate`` (counter deltas per second —
        exact at every tier because deltas are what the buckets
        hold) or ``deriv`` (per-second slope first->last bucket).
        None when no bucket falls inside the window."""
        now = time.time() if now is None else now
        window = float(window)
        ti = self.tier_for(window, tier)
        cutoff = now - window
        with self._lock:
            matched = self._match(series, labels)
            mono = []       # deltas
            buckets = []    # (t, count, sum, min, max, last)
            for s in matched:
                for p in s.rings[ti]:
                    if p[0] < cutoff:
                        continue
                    if s.monotone:
                        mono.append((p[0], p[1]))
                    else:
                        buckets.append(tuple(p))
        if agg == "rate":
            if not mono:
                return None
            return sum(v for _, v in mono) / window
        if agg == "sum":
            if mono:
                return sum(v for _, v in mono)
            return sum(b[2] for b in buckets) if buckets else None
        if agg == "deriv":
            rows = sorted(mono) if mono \
                else sorted((b[0], b[5]) for b in buckets)
            if len(rows) < 2 or rows[-1][0] <= rows[0][0]:
                return None
            return (rows[-1][1] - rows[0][1]) \
                / (rows[-1][0] - rows[0][0])
        if not buckets:
            if not mono:
                return None
            # counters answer avg/min/max over their per-bucket deltas
            vals = [v for _, v in mono]
            buckets = [(t, 1, v, v, v, v) for t, v in mono]
            del vals
        if agg == "avg":
            n = sum(b[1] for b in buckets)
            return sum(b[2] for b in buckets) / n if n else None
        if agg == "min":
            return min(b[3] for b in buckets)
        if agg == "max":
            return max(b[4] for b in buckets)
        if agg == "last":
            return max(buckets)[5]
        q = agg
        if isinstance(q, str) and q.startswith("p"):
            q = float(q[1:]) / 100.0
        q = float(q)
        if not 0.0 < q <= 1.0:
            raise ValueError("unknown agg %r" % (agg,))
        return nearest_rank(sorted(b[5] for b in buckets), q)

    # -- surfaces ----------------------------------------------------------

    def series_names(self):
        with self._lock:
            return sorted({name for name, _ in self._series})

    def stats(self):
        with self._lock:
            n = len(self._series)
        return {
            "name": self.name,
            "tiers": [{"step_s": s, "retention_s": r}
                      for s, r in self.tiers],
            "series": n,
            "max_series": self.max_series,
            "samples": self.samples,
            "dropped_series": self.dropped_series,
            "evicted_series": self.evicted_series,
            "bytes_used": self.bytes_used(),
            "max_bytes": self.max_bytes,
        }

    def history(self, series=None, labels=None, window=60.0,
                agg="avg", tier=None, now=None):
        """The ``GET /metrics/history`` payload: without ``series``,
        the store's catalog (series names + tier table + bounds
        counters); with one, the windowed aggregate plus the raw
        bucket points the query aggregated over."""
        if not series:
            out = self.stats()
            out["series_names"] = self.series_names()
            return out
        try:
            value = self.range(series, labels=labels, window=window,
                               agg=agg, now=now, tier=tier)
        except ValueError as e:
            return {"error": str(e)}
        ti = self.tier_for(float(window), tier)
        return {
            "series": series,
            "labels": dict(labels or {}),
            "window_s": float(window),
            "agg": str(agg),
            "tier": ti,
            "tier_step_s": self.tiers[ti][0],
            "value": value,
            "points": [(round(t, 3), v) for t, v in self.points(
                series, labels=labels, window=window, tier=tier,
                now=now)],
        }


def history_query(store, query):
    """Answer a ``GET /metrics/history`` query string against a
    store — the one parser both the replica endpoint and the router
    endpoint share.  Parameters: ``series`` (none = the catalog),
    ``window`` (seconds), ``agg`` (avg/min/max/last/sum/rate/deriv/
    pNN), ``tier`` (force one), plus ``label.<name>=<value>``
    selectors."""
    from urllib.parse import parse_qs
    params = {k: v[-1] for k, v in parse_qs(query or "").items()}
    labels = {k[6:]: v for k, v in params.items()
              if k.startswith("label.")}
    try:
        window = float(params.get("window", 60.0))
        tier = params.get("tier")
        tier = int(tier) if tier is not None else None
    except ValueError:
        return {"error": "bad window/tier"}
    return store.history(series=params.get("series"),
                         labels=labels or None, window=window,
                         agg=params.get("agg", "avg"), tier=tier)


def store_enabled():
    """``root.common.tsdb.enabled`` (default True) — gates the
    background samplers the replica/router tiers arm, never the
    query API of a store a test built by hand."""
    return bool(_tsdb_conf("enabled", True))


# -- the weak store registry (flight recorder / alert engines) --------------

import weakref  # noqa: E402  (registry helpers mirror alerts.py)

_stores = {}
_slock = threading.Lock()


def register_store(store):
    """Weakly register a store so process-wide surfaces (the flight
    recorder's bundle, the alert grammar's default resolution) can
    find history without owning any store's lifecycle."""
    with _slock:
        _stores[id(store)] = weakref.ref(store)


def live_stores():
    with _slock:
        items = list(_stores.items())
    out = []
    for key, ref in items:
        store = ref()
        if store is None:
            with _slock:
                _stores.pop(key, None)
            continue
        out.append(store)
    return out


def default_store():
    """The live store an un-parameterized consumer (a replica-tier
    alert engine built without an explicit handle) reads — the most
    recently registered one, or None."""
    stores = live_stores()
    return stores[-1] if stores else None


def bundle_history(window=300.0, series=BUNDLE_SERIES):
    """Tier-0 tails of the key serving series from every live store,
    store-tagged — what a flight-recorder bundle embeds so a hang
    dump shows the lead-up, not just the moment of death."""
    out = {}
    for store in live_stores():
        rec = {}
        for name in series:
            try:
                pts = store.points(name, window=window, tier=0)
            except Exception:
                continue
            if pts:
                rec[name] = [(round(t, 3), v) for t, v in pts]
        if rec:
            out[store.name] = rec
    return out
