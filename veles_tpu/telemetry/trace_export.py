"""JSONL span log → Chrome ``trace_event`` JSON.

The EventSink's JSONL file (``EventSink.open``) is greppable but not
visual; this converter turns a recorded run into the Chrome trace
format that https://ui.perfetto.dev (and ``chrome://tracing``) load
directly::

    python -m veles_tpu.telemetry.trace_export run.jsonl trace.json

Mapping:

- ``begin``/``end`` events → ``B``/``E`` phase pairs (Perfetto nests
  them per pid/tid track, so per-unit spans stack under the workflow
  run span);
- ``single`` events with a ``duration`` → ``X`` complete events
  (``ts`` backdated by the duration so the bar ends at record time);
- other ``single`` events → ``i`` instants;
- remaining attributes ride along as ``args`` (visible on click).

Timestamps are microseconds relative to the first event, keeping the
numbers readable in the UI.
"""

import json
import logging
import sys

from veles_tpu.telemetry.spans import iter_spans

_META = ("name", "kind", "time", "pid", "tid")


def _args(ev):
    return {k: v for k, v in ev.items() if k not in _META}


def spans_to_chrome(events, t0=None):
    """Convert an iterable of span-event dicts to a list of Chrome
    trace events.  ``t0`` pins the timeline origin (defaults to the
    first event's timestamp)."""
    out = []
    for ev in events:
        try:
            t = float(ev["time"])
            kind = ev["kind"]
            name = str(ev["name"])
        except (KeyError, TypeError, ValueError):
            continue
        if t0 is None:
            t0 = t
        pid = int(ev.get("pid", 0))
        tid = int(ev.get("tid", 0))
        ts = (t - t0) * 1e6
        cat = str(ev.get("cls", ev.get("unit", "span")))
        if kind == "begin":
            out.append({"name": name, "ph": "B", "ts": ts, "pid": pid,
                        "tid": tid, "cat": cat, "args": _args(ev)})
        elif kind == "end":
            out.append({"name": name, "ph": "E", "ts": ts, "pid": pid,
                        "tid": tid, "cat": cat, "args": _args(ev)})
        elif kind == "single" and ev.get("duration") is not None:
            try:
                dur = float(ev["duration"]) * 1e6
            except (TypeError, ValueError):
                continue
            out.append({"name": name, "ph": "X", "ts": ts - dur,
                        "dur": dur, "pid": pid, "tid": tid,
                        "cat": cat, "args": _args(ev)})
        else:
            out.append({"name": name, "ph": "i", "ts": ts, "pid": pid,
                        "tid": tid, "cat": cat, "s": "t",
                        "args": _args(ev)})
    return out


def export(in_path, out_path):
    """Convert the JSONL span log at ``in_path`` into a Chrome trace
    JSON at ``out_path``; returns the number of trace events.

    Corrupt/truncated lines (a crashed writer's torn tail) are
    counted and warned about, never fatal — the point of a flight
    recording is that it converts AFTER the crash."""
    stats = {}
    trace = {
        "traceEvents": spans_to_chrome(iter_spans(in_path, stats)),
        "displayTimeUnit": "ms",
        "otherData": {"source": "veles_tpu.telemetry.trace_export",
                      "input": str(in_path)},
    }
    skipped = stats.get("skipped", 0)
    if skipped:
        trace["otherData"]["skipped_lines"] = skipped
        logging.getLogger("trace_export").warning(
            "%s: skipped %d corrupt/truncated line(s) — likely a "
            "crash-torn tail; the remaining %d events converted",
            in_path, skipped, len(trace["traceEvents"]))
    with open(out_path, "w") as f:
        json.dump(trace, f)
        f.write("\n")
    return len(trace["traceEvents"])


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 2:
        print("usage: python -m veles_tpu.telemetry.trace_export "
              "<run.jsonl> <trace.json>", file=sys.stderr)
        return 2
    n = export(argv[0], argv[1])
    print("wrote %d trace events to %s (open in "
          "https://ui.perfetto.dev)" % (n, argv[1]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
