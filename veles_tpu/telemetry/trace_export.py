"""JSONL span log → Chrome ``trace_event`` JSON.

The EventSink's JSONL file (``EventSink.open``) is greppable but not
visual; this converter turns a recorded run into the Chrome trace
format that https://ui.perfetto.dev (and ``chrome://tracing``) load
directly::

    python -m veles_tpu.telemetry.trace_export run.jsonl trace.json

Mapping:

- ``begin``/``end`` events → ``B``/``E`` phase pairs (Perfetto nests
  them per pid/tid track, so per-unit spans stack under the workflow
  run span);
- ``single`` events with a ``duration`` → ``X`` complete events
  (``ts`` backdated by the duration so the bar ends at record time);
- other ``single`` events → ``i`` instants;
- remaining attributes ride along as ``args`` (visible on click).

Timestamps are microseconds relative to the first event, keeping the
numbers readable in the UI.

**Per-request mode** (``--request``) answers "why did THIS request
take 3 s": it merges the router's JSONL log with N replica logs,
keeps only the events carrying the trace id (``trace`` attr, or the
id inside a batched ``req.step`` span's ``traces`` map), and emits
ONE timeline track where the ``router.request`` span parents its
``router.attempt`` children, which in turn parent the winning
replica's queue → admit → prefill → step → retire phases by time
containment::

    python -m veles_tpu.telemetry.trace_export --request <id> \\
        -o trace.json router.jsonl replica0.jsonl replica1.jsonl

Merging logs from different processes mixes clock domains: a replica
log whose events land BEFORE the router attempt that produced them
(wallclock skew, or a writer that recorded monotonic stamps) would
silently render a misordered timeline.  Per-request mode detects
that per source file, shifts the file's events to just after their
parenting attempt (matched by replica pid when the replica id is
the default ``pid<N>:<port>`` shape, else the request edge), WARNS,
and counts the shifts in ``otherData.skew_adjusted`` — loud, not
silent.
"""

import json
import logging
import sys

from veles_tpu.telemetry.spans import iter_spans

_META = ("name", "kind", "time", "pid", "tid")


def _args(ev):
    return {k: v for k, v in ev.items() if k not in _META}


def spans_to_chrome(events, t0=None):
    """Convert an iterable of span-event dicts to a list of Chrome
    trace events.  ``t0`` pins the timeline origin (defaults to the
    first event's timestamp)."""
    out = []
    for ev in events:
        try:
            t = float(ev["time"])
            kind = ev["kind"]
            name = str(ev["name"])
        except (KeyError, TypeError, ValueError):
            continue
        if t0 is None:
            t0 = t
        pid = int(ev.get("pid", 0))
        tid = int(ev.get("tid", 0))
        ts = (t - t0) * 1e6
        cat = str(ev.get("cls", ev.get("unit", "span")))
        if kind == "begin":
            out.append({"name": name, "ph": "B", "ts": ts, "pid": pid,
                        "tid": tid, "cat": cat, "args": _args(ev)})
        elif kind == "end":
            out.append({"name": name, "ph": "E", "ts": ts, "pid": pid,
                        "tid": tid, "cat": cat, "args": _args(ev)})
        elif kind == "single" and ev.get("duration") is not None:
            try:
                dur = float(ev["duration"]) * 1e6
            except (TypeError, ValueError):
                continue
            out.append({"name": name, "ph": "X", "ts": ts - dur,
                        "dur": dur, "pid": pid, "tid": tid,
                        "cat": cat, "args": _args(ev)})
        else:
            out.append({"name": name, "ph": "i", "ts": ts, "pid": pid,
                        "tid": tid, "cat": cat, "s": "t",
                        "args": _args(ev)})
    return out


def export(in_path, out_path):
    """Convert the JSONL span log at ``in_path`` into a Chrome trace
    JSON at ``out_path``; returns the number of trace events.

    Corrupt/truncated lines (a crashed writer's torn tail) are
    counted and warned about, never fatal — the point of a flight
    recording is that it converts AFTER the crash."""
    stats = {}
    trace = {
        "traceEvents": spans_to_chrome(iter_spans(in_path, stats)),
        "displayTimeUnit": "ms",
        "otherData": {"source": "veles_tpu.telemetry.trace_export",
                      "input": str(in_path)},
    }
    skipped = stats.get("skipped", 0)
    if skipped:
        trace["otherData"]["skipped_lines"] = skipped
        logging.getLogger("trace_export").warning(
            "%s: skipped %d corrupt/truncated line(s) — likely a "
            "crash-torn tail; the remaining %d events converted",
            in_path, skipped, len(trace["traceEvents"]))
    with open(out_path, "w") as f:
        json.dump(trace, f)
        f.write("\n")
    return len(trace["traceEvents"])


# -- per-request merge (--request) --------------------------------------------

def _request_events(path, trace_id, stats):
    """The events of one JSONL file that belong to ``trace_id``: a
    matching ``trace`` attr, or membership in a batched ``req.step``
    span's ``traces`` map (projected down to this request's token
    count)."""
    out = []
    for ev in iter_spans(path, stats):
        if ev.get("trace") == trace_id:
            out.append(dict(ev))
            continue
        traces = ev.get("traces")
        if isinstance(traces, dict) and trace_id in traces:
            ev = dict(ev)
            ev["tokens"] = ev.pop("traces")[trace_id]
            ev["trace"] = trace_id
            out.append(ev)
    return out


def _attempt_windows(events):
    """(begin_time, replica) per ``router.attempt`` begin event —
    the parent candidates a replica file's spans nest under."""
    return [(float(ev["time"]), str(ev.get("replica", "")))
            for ev in events
            if ev.get("name") == "router.attempt"
            and ev.get("kind") == "begin" and "time" in ev]


def _adjust_skew(per_file, log):
    """Shift replica files whose events PRECEDE the router span that
    parents them (clock skew / a monotonic-stamped writer) so the
    merged timeline nests instead of misordering.  Returns the shift
    count; mutates event times in place."""
    router_events = []
    for path, events in per_file:
        if any(str(ev.get("name", "")).startswith("router.")
               for ev in events):
            router_events.extend(events)
    if not router_events:
        return 0  # single-process log (or no router leg recorded)
    begins = [float(ev["time"]) for ev in router_events
              if ev.get("name") == "router.request"
              and ev.get("kind") == "begin" and "time" in ev]
    edge = min(begins) if begins \
        else min(float(ev["time"]) for ev in router_events
                 if "time" in ev)
    attempts = _attempt_windows(router_events)
    adjusted = 0
    for path, events in per_file:
        if not events \
                or any(str(ev.get("name", "")).startswith("router.")
                       for ev in events):
            continue  # router-side (or empty) file: the reference
        times = []
        for ev in events:
            if "time" not in ev:
                continue
            t = float(ev["time"])
            try:
                # a single with a duration RENDERS from time - dur
                # (backdated complete slice) — align that edge, not
                # the record stamp, or the shifted span still pokes
                # out before its parent
                t -= float(ev.get("duration") or 0.0)
            except (TypeError, ValueError):
                pass
            times.append(t)
        if not times:
            continue
        t_first = min(times)
        # the parenting attempt: matched by the replica-id pid
        # convention ("pid<N>:<port>") when it holds, else the edge
        parent = edge
        pids = {ev.get("pid") for ev in events if "pid" in ev}
        matched = [t for t, rid in attempts
                   if any(rid.startswith("pid%d:" % p)
                          for p in pids if p is not None)]
        if matched:
            parent = min(matched)
        if t_first >= parent:
            continue
        shift = parent - t_first + 1e-4
        for ev in events:
            if "time" in ev:
                ev["time"] = float(ev["time"]) + shift
        adjusted += 1
        log.warning(
            "%s: events for this request start %.3fs BEFORE the "
            "router span that parents them (clock skew or a "
            "monotonic-vs-wallclock mix) — shifted +%.3fs to nest",
            path, parent - t_first, shift)
    return adjusted


def _complete_events(events, t0):
    """One flat timeline track: begin/end pairs matched by span id
    into ``X`` complete slices, singles with a duration backdated
    into ``X``, the rest ``i`` instants.  A single track makes time
    containment THE parent relation — the router attempt slice
    visually parents the replica phase slices inside it."""
    out = []
    open_spans = {}
    for ev in sorted(events, key=lambda e: float(e.get("time", 0))):
        try:
            t = float(ev["time"])
            kind = ev["kind"]
            name = str(ev["name"])
        except (KeyError, TypeError, ValueError):
            continue
        cat = "router" if name.startswith("router.") else "replica"
        base = {"name": name, "pid": 0, "tid": 0, "cat": cat}
        if kind == "begin":
            open_spans[ev.get("span")] = (t, ev)
        elif kind == "end":
            pair = open_spans.pop(ev.get("span"), None)
            if pair is None:
                out.append({**base, "ph": "i", "ts": (t - t0) * 1e6,
                            "s": "t", "args": _args(ev)})
                continue
            tb, bev = pair
            args = _args(bev)
            args.update(_args(ev))
            args.pop("span", None)
            out.append({**base, "ph": "X", "ts": (tb - t0) * 1e6,
                        "dur": max(0.0, (t - tb) * 1e6),
                        "args": args})
        elif ev.get("duration") is not None:
            try:
                dur = float(ev["duration"]) * 1e6
            except (TypeError, ValueError):
                continue
            out.append({**base, "ph": "X", "ts": (t - t0) * 1e6 - dur,
                        "dur": dur, "args": _args(ev)})
        else:
            out.append({**base, "ph": "i", "ts": (t - t0) * 1e6,
                        "s": "t", "args": _args(ev)})
    for span, (tb, bev) in open_spans.items():  # crash-torn begins
        out.append({"name": str(bev.get("name")), "pid": 0, "tid": 0,
                    "cat": "span", "ph": "i", "ts": (tb - t0) * 1e6,
                    "s": "t", "args": _args(bev)})
    out.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
    return out


def export_request(paths, trace_id, out_path):
    """Merge the JSONL logs at ``paths`` (router + N replicas, any
    order) into ONE parented Chrome trace for ``trace_id`` at
    ``out_path``; returns the number of trace events.  Corrupt lines
    are counted and skipped; cross-file clock skew is warned about,
    adjusted, and counted in ``otherData.skew_adjusted``."""
    log = logging.getLogger("trace_export")
    stats = {}
    per_file = [(p, _request_events(p, trace_id, stats))
                for p in paths]
    skew = _adjust_skew(per_file, log)
    merged = [ev for _, events in per_file for ev in events]
    times = [float(ev["time"]) for ev in merged if "time" in ev]
    t0 = min(times) if times else 0.0
    trace_events = [
        {"ph": "M", "name": "process_name", "pid": 0,
         "args": {"name": "request %s" % trace_id}},
        {"ph": "M", "name": "thread_name", "pid": 0, "tid": 0,
         "args": {"name": "router -> replica timeline"}},
    ] + _complete_events(merged, t0)
    trace = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "veles_tpu.telemetry.trace_export",
            "request": trace_id,
            "inputs": [str(p) for p in paths],
            "skew_adjusted": skew,
        },
    }
    skipped = stats.get("skipped", 0)
    if skipped:
        trace["otherData"]["skipped_lines"] = skipped
        log.warning("skipped %d corrupt/truncated line(s) across "
                    "%d input file(s)", skipped, len(paths))
    if not merged:
        log.warning("no events carry trace id %r — is tracing "
                    "enabled (root.common.reqtrace.enabled) and are "
                    "these the right logs?", trace_id)
    with open(out_path, "w") as f:
        json.dump(trace, f)
        f.write("\n")
    return len(trace["traceEvents"])


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    usage = ("usage: python -m veles_tpu.telemetry.trace_export "
             "<run.jsonl> <trace.json>\n"
             "       python -m veles_tpu.telemetry.trace_export "
             "--request ID [-o trace.json] <router.jsonl> "
             "[replica.jsonl ...]")
    if "--request" in argv:
        i = argv.index("--request")
        try:
            trace_id = argv[i + 1]
        except IndexError:
            print(usage, file=sys.stderr)
            return 2
        del argv[i:i + 2]
        out_path = "trace-%s.json" % trace_id
        if "-o" in argv:
            j = argv.index("-o")
            try:
                out_path = argv[j + 1]
            except IndexError:
                print(usage, file=sys.stderr)
                return 2
            del argv[j:j + 2]
        if not argv:
            print(usage, file=sys.stderr)
            return 2
        n = export_request(argv, trace_id, out_path)
        print("wrote %d trace events for request %s to %s (open in "
              "https://ui.perfetto.dev)" % (n, trace_id, out_path))
        return 0
    if len(argv) != 2:
        print(usage, file=sys.stderr)
        return 2
    n = export(argv[0], argv[1])
    print("wrote %d trace events to %s (open in "
          "https://ui.perfetto.dev)" % (n, argv[1]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
