"""Span pipeline — structured begin/end tracing over the EventSink.

The :class:`veles_tpu.logger.EventSink` records raw ``begin``/``end``/
``single`` events; this module adds the *workflow tracing* contract on
top:

- :func:`span` — a context manager emitting a ``begin``/``end`` pair
  that shares a unique ``span`` id, with the measured ``duration``
  (seconds) attached to the ``end`` event, so every begin can be paired
  with its end even across interleaved threads;
- :func:`iter_spans` — stream a recorded JSONL span log back as dicts
  (the reader side used by :mod:`veles_tpu.telemetry.trace_export`).

The per-unit spans the scheduler emits (``unit:<name>`` in
:meth:`veles_tpu.units.Unit._run_wrapped`) follow the same schema.
"""

import itertools
import json
import os
import time

from veles_tpu.logger import events as default_sink

_span_ids = itertools.count(1)


def next_span_id():
    """Process-unique span id (pid-qualified so merged logs from a
    coordinator fleet keep their pairs distinct)."""
    return "%d-%d" % (os.getpid(), next(_span_ids))


class span:
    """Context manager emitting a paired begin/end span::

        with span("load checkpoint", path=p):
            ...

    The end event carries ``duration`` (seconds) and ``error`` (the
    exception type name) when the block raised."""

    def __init__(self, name, sink=None, **attrs):
        self.name = name
        self.sink = sink or default_sink
        self.attrs = attrs
        self.span_id = None
        self._t0 = None

    def __enter__(self):
        self.span_id = next_span_id()
        self._t0 = time.time()
        self.sink.record(self.name, "begin", span=self.span_id,
                         **self.attrs)
        return self

    def __exit__(self, exc_type, exc, tb):
        attrs = dict(self.attrs)
        attrs["duration"] = time.time() - self._t0
        if exc_type is not None:
            attrs["error"] = exc_type.__name__
        self.sink.record(self.name, "end", span=self.span_id, **attrs)
        return False


def iter_spans(path, stats=None):
    """Yield the events of a JSONL span log as dicts; malformed lines
    (a crashed writer's torn tail, binary garbage, non-dict JSON) are
    skipped, not fatal.  Pass a dict as ``stats`` to learn how many
    lines were dropped (``stats["skipped"]``) — the trace exporter
    reports it so a crash-truncated log converts loudly, not
    silently."""
    if stats is not None:
        stats.setdefault("skipped", 0)
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                if stats is not None:
                    stats["skipped"] += 1
                continue
            if isinstance(ev, dict):
                yield ev
            elif stats is not None:
                stats["skipped"] += 1
