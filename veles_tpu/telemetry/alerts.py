"""Alerting engine over the metrics registry and fleet state.

The fleet records everything (Prometheus families, SLO burn-rate
gauges, breaker states, watchdog trips) and until now *told* no one:
an operator had to be staring at the right scrape at the right
moment.  This module closes the loop — a low-frequency ticker thread
evaluates declarative rules over the process-wide registry and drives
a per-series ``pending → firing → resolved`` state machine with
``for_seconds`` hold-downs (one transient bad sample never pages).

**Rule grammar** (``root.common.alerts.rules``, a tuple of dicts)::

    {"name": "kv_low", "expr": "veles_serving_kv_blocks_free < 2",
     "for": 5.0, "severity": "ticket"}

``expr`` is ``[func(]family[{label=value,...}][)] OP number`` with
``OP`` one of ``> < >= <= == !=`` and ``func`` one of ``sum``,
``min``, ``max``, ``avg`` (aggregate matching series into ONE alert
instance), ``increase`` (per-series delta since the last tick —
counters) or ``rate`` (delta per second).  Without a func, every
matching series gets its OWN state machine, so one replica's breaker
firing does not mask a second replica's.

**Trend functions** (PR 17) read the embedded time-series store
(:mod:`veles_tpu.telemetry.tsdb`) instead of the instantaneous
registry, so rules can compare now against history::

    avg_over_time(family[{sel}][, window_s]) OP number
    max_over_time(family[{sel}][, window_s]) OP number
    min_over_time(family[{sel}][, window_s]) OP number
    deriv(family[{sel}][, window_s]) OP number          # per-second slope
    drop_vs_baseline(family[{sel}][, short, long]) OP number

``drop_vs_baseline`` is the regression detector: the fractional drop
of the short-window average below the long-window *median* (the
trailing baseline), 0 when the baseline is empty or non-positive —
``> 0.5`` means "running at less than half the trailing-hour
median".  Windows default to 60s (and 3600s for the baseline).  Each
stored series matching the selector keeps its own state machine;
with no live store the functions yield no rows (and a firing
instance resolves via the vanished-series path).

**Shipped defaults** (:func:`default_rules`, disable with
``root.common.alerts.defaults = False``) cover the fleet's known
failure shapes: multi-window fast+slow SLO burn (the SRE Workbook
pairing — both windows must burn before paging, so a blip neither
pages nor hides a sustained burn), breaker open, health-policy halt,
replica unreachable, KV block pressure, unfetched KV-export expiry
(a decode pool that stopped coming for its disaggregated handoffs),
watchdog stalls, prefix-hit collapse, and bucket-padding waste
("busy but wasting its batches").

**Sinks** on every fire/resolve: the JSONL event ring
(``alert.fire`` / ``alert.resolve``), the process log, the
``veles_alerts_firing{rule,severity}`` gauge, and an optional webhook
POST (``root.common.alerts.webhook_url``) guarded by the
``alerts.webhook`` fault point so chaos tests can drop or fail it.
Engines register weakly at :func:`register_engine`;
:func:`firing_table` merges every live engine's firing alerts — the
flight recorder embeds it so a hang bundle says what was already
wrong *before* the hang.

``GET /alerts`` on the router, the serving replicas and the
web-status dashboard all serve :meth:`AlertEngine.snapshot`.
"""

import json
import re
import threading
import time
import urllib.request
import weakref
from collections import deque

from veles_tpu import faults
from veles_tpu.logger import Logger, events
from veles_tpu.telemetry.registry import metrics as default_registry

__all__ = ("AlertRule", "AlertEngine", "default_rules",
           "register_engine", "firing_table")

SEVERITIES = ("info", "ticket", "page")

_EXPR = re.compile(
    r'^\s*(?:(sum|min|max|avg|increase|rate)\s*\(\s*)?'
    r'([A-Za-z_:][A-Za-z0-9_:]*)\s*(?:\{([^}]*)\})?\s*\)?\s*'
    r'(>=|<=|==|!=|>|<)\s*'
    r'(-?(?:\d+\.?\d*|\.\d+)(?:[eE]-?\d+)?)\s*$')
# the tsdb-backed trend functions: windows are positional seconds
# (avg/max/min_over_time + deriv take one, drop_vs_baseline takes
# short, long).  Tried BEFORE _EXPR so "deriv(...)" never half-parses
# as a bare family read.
_EXPR_TIME = re.compile(
    r'^\s*(avg_over_time|max_over_time|min_over_time|deriv|'
    r'drop_vs_baseline)\s*\(\s*'
    r'([A-Za-z_:][A-Za-z0-9_:]*)\s*(?:\{([^}]*)\})?\s*'
    r'(?:,\s*(\d+\.?\d*)\s*)?(?:,\s*(\d+\.?\d*)\s*)?\)\s*'
    r'(>=|<=|==|!=|>|<)\s*'
    r'(-?(?:\d+\.?\d*|\.\d+)(?:[eE]-?\d+)?)\s*$')
_SEL_LABEL = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)\s*=\s*'
                        r'"?([^",}]*)"?')

_OPS = {
    ">": lambda a, b: a > b, "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b, "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
}


def _alerts_conf(name, default):
    from veles_tpu.config import root
    return root.common.alerts.get(name, default)


class AlertRule:
    """One declarative rule: an ``expr`` over registry families, or a
    built-in ``kind`` evaluator (``slo_burn`` — the fast+slow
    multi-window pair).  ``for_seconds`` is the pending hold-down
    before an instance may fire."""

    def __init__(self, name, expr=None, severity="ticket",
                 for_seconds=0.0, kind="expr", params=None,
                 description=""):
        self.name = str(name)
        if severity not in SEVERITIES:
            raise ValueError("severity %r not in %s"
                             % (severity, SEVERITIES))
        self.severity = severity
        self.for_seconds = float(for_seconds)
        self.kind = kind
        self.params = dict(params or {})
        self.description = description
        self.expr = expr
        self._parsed = None
        self._time_memo = None   # (store id+sample count, rows)
        if kind == "expr":
            if not expr:
                raise ValueError("rule %s: expr required" % name)
            mt = _EXPR_TIME.match(expr)
            if mt is not None:
                func, family, selector, w1, w2, op, threshold = \
                    mt.groups()
                self._parsed = {
                    "func": func, "family": family,
                    "selector": dict(
                        _SEL_LABEL.findall(selector or "")),
                    "op": op, "threshold": float(threshold),
                    "time": True,
                    "w1": float(w1) if w1 else None,
                    "w2": float(w2) if w2 else None}
                return
            m = _EXPR.match(expr)
            if m is None:
                raise ValueError("rule %s: cannot parse expr %r"
                                 % (name, expr))
            func, family, selector, op, threshold = m.groups()
            self._parsed = {
                "func": func, "family": family,
                "selector": dict(_SEL_LABEL.findall(selector or "")),
                "op": op, "threshold": float(threshold)}
        elif kind != "slo_burn":
            raise ValueError("rule %s: unknown kind %r" % (name, kind))

    @classmethod
    def from_dict(cls, spec):
        spec = dict(spec)
        return cls(spec.pop("name"),
                   expr=spec.pop("expr", None),
                   severity=spec.pop("severity", "ticket"),
                   for_seconds=float(spec.pop("for", 0.0)),
                   kind=spec.pop("kind", "expr"),
                   description=spec.pop("description", ""),
                   params=spec or None)

    def describe(self):
        return {"name": self.name, "severity": self.severity,
                "for_seconds": self.for_seconds, "kind": self.kind,
                "expr": self.expr, "params": self.params or None,
                "description": self.description or None}

    # -- evaluation --------------------------------------------------------

    def _series(self, registry):
        """[(labels dict, value)] for the rule's family, restricted
        to the selector.  Histograms contribute their ``_count``."""
        from veles_tpu.telemetry.registry import Histogram, _Family
        fam = registry.get(self._parsed["family"])
        if fam is None:
            return []
        sel = self._parsed["selector"]
        rows = []
        if isinstance(fam, _Family):
            for lv, child in fam.children().items():
                rows.append((dict(zip(fam.labelnames, lv)), child))
        else:
            rows.append(({}, fam))
        out = []
        for labels, child in rows:
            if any(labels.get(k) != v for k, v in sel.items()):
                continue
            try:
                value = child.count if isinstance(child, Histogram) \
                    else child.value
            except Exception:
                continue
            out.append((labels, float(value)))
        return out

    def evaluate(self, registry, prev, dt, tsdb=None):
        """[(labels dict, value, condition bool)] — one entry per
        alert instance this tick.  ``prev`` is the engine's
        per-series memory for increase/rate (first sight reads as
        delta 0, so restarts never page on a counter's history);
        ``tsdb`` is the history store the trend functions query."""
        if self.kind == "slo_burn":
            return self._evaluate_slo_burn(registry)
        if self._parsed.get("time"):
            # the store only gains data once per sampling interval,
            # so between samples the answer cannot change — memoize
            # on the sample counter (engines often tick much faster
            # than the store samples, e.g. 20 Hz test intervals
            # against the 1 Hz tier-0 ticker)
            key = (id(tsdb), tsdb.samples) if tsdb is not None \
                else None
            cached = self._time_memo
            if cached is not None and cached[0] == key:
                return cached[1]
            rows = self._evaluate_time(tsdb)
            self._time_memo = (key, rows)
            return rows
        p = self._parsed
        cmp_, thr = _OPS[p["op"]], p["threshold"]
        rows = self._series(registry)
        if p["func"] in ("increase", "rate"):
            out = []
            for labels, value in rows:
                key = (self.name, tuple(sorted(labels.items())))
                last = prev.get(key)
                prev[key] = value
                delta = max(0.0, value - last) \
                    if last is not None else 0.0
                if p["func"] == "rate":
                    delta = delta / dt if dt > 0 else 0.0
                out.append((labels, delta, cmp_(delta, thr)))
            return out
        if p["func"]:
            vals = [v for _, v in rows if v == v]  # drop NaNs
            if not vals:
                return [(dict(p["selector"]), float("nan"), False)]
            agg = {"sum": sum, "min": min, "max": max,
                   "avg": lambda v: sum(v) / len(v)}[p["func"]](vals)
            return [(dict(p["selector"]), agg, cmp_(agg, thr))]
        return [(labels, v, v == v and cmp_(v, thr))
                for labels, v in rows]

    def _evaluate_time(self, store):
        """The tsdb-backed trend functions: one row per stored
        series matching the selector.  No live store (or no data in
        the window) yields no rows — a firing instance then resolves
        through the vanished-series path instead of latching."""
        if store is None:
            return []
        p = self._parsed
        cmp_, thr = _OPS[p["op"]], p["threshold"]
        family, sel, func = p["family"], p["selector"], p["func"]
        w1 = p["w1"] if p["w1"] is not None else 60.0
        out = []
        for labels in store.label_sets(family, sel):
            try:
                if func == "drop_vs_baseline":
                    long_ = p["w2"] if p["w2"] is not None else 3600.0
                    base = store.range(family, labels, window=long_,
                                       agg=0.5)
                    recent = store.range(family, labels, window=w1,
                                         agg="avg")
                    if base is None or recent is None or base <= 0:
                        value = 0.0
                    else:
                        value = (base - recent) / base
                else:
                    agg = {"avg_over_time": "avg",
                           "max_over_time": "max",
                           "min_over_time": "min",
                           "deriv": "deriv"}[func]
                    value = store.range(family, labels, window=w1,
                                        agg=agg)
            except Exception:
                continue
            if value is None:
                continue
            out.append((labels, value, cmp_(value, thr)))
        return out

    def _evaluate_slo_burn(self, registry):
        """The SRE multi-window pair: one instance per
        ``(scope, cls, slo)`` series group of ``veles_slo_burn_rate``;
        the condition needs BOTH the fast and the slow window above
        the threshold factor."""
        from veles_tpu.telemetry.registry import _Family
        fam = registry.get(self.params.get(
            "family", "veles_slo_burn_rate"))
        if not isinstance(fam, _Family):
            return []
        fast = str(self.params.get("fast", "60s"))
        slow = str(self.params.get("slow", "300s"))
        thr = float(self.params.get("threshold", 14.4))
        groups = {}
        for lv, child in fam.children().items():
            labels = dict(zip(fam.labelnames, lv))
            w = labels.pop("window", None)
            if w not in (fast, slow):
                continue
            key = tuple(sorted(labels.items()))
            try:
                groups.setdefault(key, {})[w] = float(child.value)
            except Exception:
                continue
        out = []
        for key, by_window in sorted(groups.items()):
            burn_fast = by_window.get(fast, 0.0)
            burn_slow = by_window.get(slow, 0.0)
            cond = burn_fast > thr and burn_slow > thr
            labels = dict(key)
            labels["window"] = "%s+%s" % (fast, slow)
            out.append((labels, max(burn_fast, burn_slow), cond))
        return out


def default_rules():
    """The shipped rule set — every known fleet failure shape pages
    or tickets out of the box (docs/observability.md has the table;
    docs/robustness.md maps episodes to the rule that fires)."""
    return [
        AlertRule(
            "slo_burn_page", kind="slo_burn", severity="page",
            for_seconds=0.0,
            params={"fast": "60s", "slow": "300s",
                    "threshold": 14.4},
            description="error budget burning >=14.4x over BOTH the "
                        "60s and 300s windows — at this rate a 99% "
                        "monthly budget dies in ~2 days"),
        AlertRule(
            "slo_burn_ticket", kind="slo_burn", severity="ticket",
            for_seconds=0.0,
            params={"fast": "300s", "slow": "3600s",
                    "threshold": 3.0},
            description="sustained 3x budget burn over 300s+3600s — "
                        "not page-worthy, but trending to exhaustion"),
        AlertRule(
            "breaker_open", severity="page", for_seconds=1.0,
            expr="veles_router_breaker_state >= 2",
            description="a replica's circuit breaker is open: "
                        "consecutive forward failures took it out of "
                        "rotation"),
        AlertRule(
            "health_halt", severity="page", for_seconds=0.0,
            expr="veles_health_status >= 2",
            description="the training-health policy latched halted "
                        "(non-finite loss/grads) — the process is up "
                        "for forensics but not servable"),
        AlertRule(
            "replica_unreachable", severity="page", for_seconds=1.0,
            expr="veles_router_replica_up == 0",
            description="the router's health poll cannot reach a "
                        "replica (two strikes — out of rotation)"),
        AlertRule(
            "kv_block_pressure", severity="ticket", for_seconds=2.0,
            expr="veles_serving_kv_pressure > 0.92",
            description="paged-KV pool >92% occupied — admissions "
                        "start shedding/preempting soon"),
        AlertRule(
            "kv_export_expiry", severity="ticket", for_seconds=0.0,
            expr="increase(veles_serving_kv_export_expired_total)"
                 " > 0",
            description="disaggregated KV-export records are "
                        "expiring unfetched — the decode pool is "
                        "not coming for its handoffs (dead decode "
                        "specialists, a partitioned router, or a "
                        "role pool that emptied)"),
        AlertRule(
            "watchdog_stall", severity="page", for_seconds=0.0,
            expr="increase(veles_serving_watchdog_trips_total) > 0",
            description="the decode-loop watchdog tripped: a stalled "
                        "step failed its pending requests"),
        AlertRule(
            "prefix_hit_collapse", severity="ticket",
            for_seconds=5.0,
            expr="veles_serving_prefix_hit_rate_recent < 0.05",
            description="radix prefix-cache hit rate collapsed under "
                        "real lookup traffic — affinity routing or "
                        "the cache itself regressed"),
        AlertRule(
            "bucket_padding_waste", severity="info",
            for_seconds=10.0,
            expr="veles_serving_bucket_padding_efficiency < 0.35",
            description="the fleet is busy but wasting its batches: "
                        "most padded positions carry no request"),
        AlertRule(
            "controller_flapping", severity="ticket",
            for_seconds=5.0,
            expr="increase(veles_controller_scale_transitions_total)"
                 " > 2",
            description="the fleet controller is scaling up AND down "
                        "inside one evaluation window — its "
                        "thresholds/cooldowns are mis-tuned and "
                        "replicas are churning instead of serving"),
        AlertRule(
            "tenant_throttled", severity="info",
            for_seconds=5.0,
            expr="rate(veles_router_tenant_throttled_total) > 1",
            description="a tenant is being 429'd at a sustained "
                        "rate (token bucket or concurrency lane) — "
                        "either a flood the lane is correctly "
                        "containing, or a limit set too tight for a "
                        "legitimate client (per-series: one state "
                        "machine per bounded tenant label)"),
        AlertRule(
            "goodput_regression", severity="ticket",
            for_seconds=5.0,
            expr="drop_vs_baseline("
                 "veles_serving_goodput_tokens_per_sec, 60, 3600)"
                 " > 0.5",
            description="goodput over the last minute is running at "
                        "less than half the trailing-hour median — "
                        "a regression the instantaneous gauge can't "
                        "see (it has no memory of what 'normal' "
                        "was); catches slow-burn degradations like "
                        "a shrinking batch or a sick replica "
                        "dragging the fleet"),
        AlertRule(
            "ttft_p95_creep", severity="ticket", for_seconds=10.0,
            expr="deriv(veles_serving_ttft_p95_ms, 600) > 1.0",
            description="TTFT p95 climbing >1ms/s sustained over 10 "
                        "minutes — queueing or prefill pressure "
                        "building faster than the fleet absorbs it "
                        "(the creep an instantaneous threshold "
                        "misses until it's already an SLO burn)"),
        AlertRule(
            "kv_pressure_growth", severity="info",
            for_seconds=10.0,
            expr="deriv(veles_serving_kv_pressure, 300) > 0.001",
            description="paged-KV occupancy growing monotonically "
                        "over 5 minutes — long-lived streams are "
                        "accumulating toward the shed threshold; "
                        "heads-up before kv_block_pressure tickets"),
        AlertRule(
            "kv_host_thrash", severity="ticket", for_seconds=5.0,
            expr="avg_over_time("
                 "veles_serving_kv_host_thrash_rate, 60) > 2",
            description="the host KV tier is churning: blocks are "
                        "demoting AND promoting back at a sustained "
                        "rate (min of the two, blocks/s) — the "
                        "working set exceeds device capacity and "
                        "the tier is paging instead of caching; "
                        "grow kv_host_bytes' device budget "
                        "(kv_blocks), spread load, or expect "
                        "staging-gather overhead on every warm "
                        "admission"),
    ]


def _firing_series():
    return {
        "firing": default_registry.gauge(
            "veles_alerts_firing",
            "currently firing alert instances, by rule and severity",
            labelnames=("rule", "severity")),
        "transitions": default_registry.counter(
            "veles_alerts_transitions_total",
            "alert state-machine transitions, by rule and new state",
            labelnames=("rule", "to")),
    }


class _Instance:
    """One (rule, label set) state machine."""

    __slots__ = ("labels", "state", "since", "fired_at", "value")

    def __init__(self, labels):
        self.labels = labels
        self.state = "ok"       # ok | pending | firing
        self.since = None       # first-true time of this episode
        self.fired_at = None
        self.value = None


class AlertEngine(Logger):
    """Evaluate rules on a ticker thread; serve snapshots.

    ``providers`` maps extra context names to zero-arg callables whose
    dicts ride into :meth:`snapshot` (the router passes its replica
    table) — rules themselves read only the registry, so the engine
    never blocks on a provider."""

    def __init__(self, name="alerts", rules=None, registry=None,
                 interval=None, webhook_url=None, providers=None,
                 resolved_keep=64, tsdb=None):
        super(AlertEngine, self).__init__()
        self.name = str(name)
        self.registry = registry if registry is not None \
            else default_registry
        self.tsdb = tsdb        # None -> resolve a live store per tick
        self.interval = float(_alerts_conf("interval", 1.0)
                              if interval is None else interval)
        self.webhook_url = _alerts_conf("webhook_url", None) \
            if webhook_url is None else webhook_url
        if rules is None:
            rules = list(default_rules()) \
                if _alerts_conf("defaults", True) else []
            for spec in _alerts_conf("rules", ()) or ():
                rules.append(AlertRule.from_dict(spec))
        self.rules = list(rules)
        self.providers = dict(providers or {})
        self._lock = threading.Lock()
        self._instances = {}    # (rule name, labels key) -> _Instance
        self._prev = {}         # increase/rate memory
        self._last_tick = None
        self._resolved = deque(maxlen=int(resolved_keep))
        self._global = _firing_series()
        self.ticks = 0
        self.webhook_ok = 0
        self.webhook_failures = 0
        self._stop = threading.Event()
        self._thread = None
        register_engine(self)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        with self._lock:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="alerts-%s" % self.name)
                self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(5)

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:  # the ticker must outlive any rule
                self.warning("alert tick failed: %r", e)

    # -- evaluation --------------------------------------------------------

    def tick(self, now=None):
        """One evaluation pass; returns the transition events it
        emitted (tests drive the state machine through here)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            dt = (now - self._last_tick) if self._last_tick else 0.0
            self._last_tick = now
            self.ticks += 1
        store = self.tsdb
        if store is None:
            from veles_tpu.telemetry.tsdb import default_store
            store = default_store()
        transitions = []
        for rule in self.rules:
            try:
                rows = rule.evaluate(self.registry, self._prev, dt,
                                     tsdb=store)
            except Exception as e:
                self.warning("rule %s evaluation failed: %r",
                             rule.name, e)
                continue
            transitions.extend(self._advance(rule, rows, now))
        self._sync_gauges()
        for ev in transitions:
            self._emit(ev)
        return transitions

    def _advance(self, rule, rows, now):
        with self._lock:
            live = set()
            out = []
            for labels, value, cond in rows:
                key = (rule.name, tuple(sorted(labels.items())))
                live.add(key)
                inst = self._instances.get(key)
                if inst is None:
                    inst = self._instances[key] = _Instance(labels)
                inst.value = value
                if cond:
                    if inst.state == "ok":
                        inst.state = "pending"
                        inst.since = now
                    if inst.state == "pending" \
                            and now - inst.since >= rule.for_seconds:
                        inst.state = "firing"
                        inst.fired_at = now
                        out.append(("fire", rule, inst))
                else:
                    if inst.state == "firing":
                        out.append(("resolve", rule, inst))
                        self._retire(rule, inst, now)
                    if inst.state == "pending":
                        inst.state = "ok"
                        inst.since = None
            # a series that vanished (replica removed, family gone)
            # resolves rather than firing forever
            for key in [k for k in self._instances
                        if k[0] == rule.name and k not in live]:
                inst = self._instances.pop(key)
                if inst.state == "firing":
                    out.append(("resolve", rule, inst))
                    self._retire(rule, inst, now)
            return out

    def _retire(self, rule, inst, now):
        """lock held: firing -> resolved bookkeeping."""
        self._resolved.append({
            "rule": rule.name, "severity": rule.severity,
            "labels": dict(inst.labels), "value": inst.value,
            "fired_for_s": round(now - (inst.fired_at or now), 3),
            "resolved_at": time.time()})
        inst.state = "ok"
        inst.since = inst.fired_at = None

    def _sync_gauges(self):
        with self._lock:
            counts = {}
            for (rname, _), inst in self._instances.items():
                if inst.state == "firing":
                    counts[rname] = counts.get(rname, 0) + 1
        for rule in self.rules:
            self._global["firing"].labels(
                rule=rule.name, severity=rule.severity).set(
                counts.get(rule.name, 0))

    # -- sinks -------------------------------------------------------------

    def _emit(self, transition):
        what, rule, inst = transition
        payload = {"rule": rule.name, "severity": rule.severity,
                   "labels": dict(inst.labels),
                   "value": inst.value, "engine": self.name}
        events.record("alert.%s" % what, "single", cls="AlertEngine",
                      **payload)
        self._global["transitions"].labels(
            rule=rule.name, to="firing" if what == "fire"
            else "resolved").inc()
        log = self.warning if what == "fire" else self.info
        log("alert %s: %s [%s] %s value=%s", what, rule.name,
            rule.severity, inst.labels, inst.value)
        self._post_webhook(what, payload)

    def _post_webhook(self, what, payload):
        if not self.webhook_url:
            return
        try:
            if faults.fire("alerts.webhook", key=payload["rule"]):
                raise ConnectionError("injected webhook drop")
            body = dict(payload)
            body["event"] = what
            body["time"] = time.time()
            req = urllib.request.Request(
                self.webhook_url, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=2.0).read()
            self.webhook_ok += 1
        except Exception as e:
            # the webhook is a sink, never a dependency: count and
            # keep going (the JSONL/log/gauge sinks already fired)
            self.webhook_failures += 1
            self.debug("webhook POST failed: %r", e)

    # -- reads -------------------------------------------------------------

    def _rows(self, state):
        with self._lock:
            items = [(k, inst) for k, inst in self._instances.items()
                     if inst.state == state]
        by_rule = {r.name: r for r in self.rules}
        out = []
        for (rname, _), inst in sorted(items, key=lambda kv: kv[0]):
            rule = by_rule.get(rname)
            out.append({
                "rule": rname,
                "severity": rule.severity if rule else "?",
                "labels": dict(inst.labels), "value": inst.value,
                "since": inst.since,
                "firing_for_s": round(
                    time.monotonic() - inst.fired_at, 3)
                if inst.fired_at else None})
        return out

    def firing(self):
        return self._rows("firing")

    def snapshot(self):
        """The ``GET /alerts`` payload."""
        with self._lock:
            resolved = list(self._resolved)
        return {
            "engine": self.name,
            "interval_s": self.interval,
            "ticks": self.ticks,
            "webhook": {"url": self.webhook_url,
                        "ok": self.webhook_ok,
                        "failures": self.webhook_failures}
            if self.webhook_url else None,
            "rules": [r.describe() for r in self.rules],
            "firing": self.firing(),
            "pending": self._rows("pending"),
            "recent_resolved": resolved,
            "context": {name: self._provider(fn)
                        for name, fn in self.providers.items()},
        }

    @staticmethod
    def _provider(fn):
        try:
            return fn()
        except Exception as e:
            return {"error": repr(e)}


# -- the weak engine registry (flight recorder / web_status reads) ----------

_engines = {}
_elock = threading.Lock()


def register_engine(engine):
    """Weakly register an engine so process-wide surfaces (the flight
    recorder's crash bundle, web_status ``/alerts``) can enumerate
    firing alerts without owning any engine's lifecycle."""
    with _elock:
        _engines[id(engine)] = weakref.ref(engine)


def live_engines():
    with _elock:
        items = list(_engines.items())
    out = []
    for key, ref in items:
        engine = ref()
        if engine is None:
            with _elock:
                _engines.pop(key, None)
            continue
        out.append(engine)
    return out


def firing_table():
    """Every live engine's firing alerts, engine-tagged — what a
    flight-recorder bundle embeds so a hang dump says what was
    already wrong before the hang."""
    out = []
    for engine in live_engines():
        try:
            for row in engine.firing():
                row = dict(row)
                row["engine"] = engine.name
                out.append(row)
        except Exception:
            continue
    return out
