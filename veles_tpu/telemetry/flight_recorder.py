"""Crash flight recorder — post-mortem forensics without a daemon.

The repo's own history motivates this: a heap-corruption crash inside
XLA:CPU span-step execution killed whole test runs with ZERO
forensics (no stacks, no recent events, no config — just a dead
process).  The reference framework had the same blind spot: its
MongoDB event mirror died with the process that fed it.

:class:`FlightRecorder` keeps the answer *inside* the process, ready
to dump at the moment of death:

- a bounded tail of recent log records (a ``logging`` handler feeding
  a ring) rides next to the span ring the EventSink already keeps;
- :meth:`install` registers the crash paths — ``faulthandler`` for
  native faults (SIGSEGV/SIGABRT stacks to stderr, where worker logs
  already aggregate), a ``SIGUSR1`` handler for on-demand dumps of a
  live process, a chained ``sys.excepthook`` for unhandled Python
  exceptions, and an ``atexit`` hook (opt-in via
  ``root.common.flightrec.dump_on_exit``);
- :meth:`dump` writes the debug bundle —
  ``<snapshot_dir>/flightrec-<pid>.json`` — containing the recent
  span events, the full metrics-registry snapshot, the effective
  config, jax/platform environment, per-thread stacks, the health
  monitor state, the log tail, and the LIVE in-flight request table
  (``requests``: trace id, phase, age, blocks held — from every
  scheduler/router registered with
  :mod:`veles_tpu.telemetry.reqtrace`).

``GET /debug/state`` on both HTTP services serves the same bundle
ingredients from the live process (see ``docs/observability.md``).
"""

import atexit
import faulthandler
import json
import logging
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque

log = logging.getLogger("flightrec")


class _LogTail(logging.Handler):
    """Root-logger handler appending compact records to a ring."""

    def __init__(self, ring):
        super(_LogTail, self).__init__(level=logging.INFO)
        self.ring = ring

    def emit(self, record):
        try:
            self.ring.append({
                "time": record.created,
                "level": record.levelname,
                "logger": record.name,
                "message": record.getMessage(),
            })
        except Exception:  # a broken record must never break logging
            pass


class FlightRecorder:
    """Bounded event/log tail + crash hooks + bundle dumper."""

    def __init__(self, max_events=256, max_logs=256):
        self.max_events = int(max_events)
        self.log_ring = deque(maxlen=int(max_logs))
        self._lock = threading.Lock()
        self._installed = False
        self._handler = None
        self._dir = None
        self._prev_excepthook = None
        self._prev_signals = {}
        self._start = time.time()
        self.dumps = []

    # -- installation ------------------------------------------------------

    def _resolve_dir(self):
        if self._dir:
            return self._dir
        from veles_tpu.config import root
        return root.common.flightrec.get("dir") \
            or root.common.dirs.get("snapshots") or "."

    def install(self, directory=None, signals=(signal.SIGUSR1,),
                excepthook=True, enable_faulthandler=True):
        """Idempotent; safe off the main thread (signal hooks are then
        skipped with a debug note — everything else still installs)."""
        with self._lock:
            if self._installed:
                return self
            self._installed = True
            self._dir = directory
            self._handler = _LogTail(self.log_ring)
            logging.getLogger().addHandler(self._handler)
            if enable_faulthandler and not faulthandler.is_enabled():
                # native-fault stacks to stderr: worker/CI logs already
                # capture stderr, and stderr needs no open file to leak
                faulthandler.enable()
            for sig in signals:
                try:
                    self._prev_signals[sig] = signal.signal(
                        sig, self._on_signal)
                except (ValueError, OSError) as e:
                    log.debug("cannot hook signal %s: %s", sig, e)
            if excepthook:
                self._prev_excepthook = sys.excepthook
                sys.excepthook = self._excepthook
            atexit.register(self._on_exit)
        return self

    def uninstall(self):
        with self._lock:
            if not self._installed:
                return
            self._installed = False
            if self._handler is not None:
                logging.getLogger().removeHandler(self._handler)
                self._handler = None
            for sig, prev in self._prev_signals.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):
                    pass
            self._prev_signals = {}
            if self._prev_excepthook is not None:
                sys.excepthook = self._prev_excepthook
                self._prev_excepthook = None
            try:
                atexit.unregister(self._on_exit)
            except Exception:
                pass

    # -- crash paths -------------------------------------------------------

    def _on_signal(self, signum, frame):
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        self.dump("signal:%s" % name)

    def _excepthook(self, exc_type, exc, tb):
        try:
            self.dump("exception:%s" % exc_type.__name__,
                      extra={"exception": "".join(
                          traceback.format_exception(exc_type, exc,
                                                     tb))[-4000:]})
        except Exception:
            pass
        (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

    def _on_exit(self):
        try:
            from veles_tpu.config import root
            if root.common.flightrec.get("dump_on_exit"):
                self.dump("atexit")
        except Exception:
            pass

    # -- the bundle --------------------------------------------------------

    def bundle(self, reason, extra=None):
        """The debug bundle as a plain dict.  Every section guards
        itself: a dump fired from a crash path must produce whatever
        it still can, never raise."""
        info = {"reason": reason, "time": time.time(),
                "pid": os.getpid(), "argv": list(sys.argv),
                "uptime_s": round(time.time() - self._start, 3)}
        if extra:
            info.update(extra)
        try:
            import platform
            info["platform"] = {"python": sys.version.split()[0],
                                "system": platform.platform()}
        except Exception:
            pass
        info["env"] = {k: v for k, v in os.environ.items()
                       if k.startswith(("JAX", "XLA", "VELES", "TPU",
                                        "LIBTPU", "CUDA_VISIBLE"))}
        # never IMPORT jax from a crash handler — only describe it when
        # the process already paid for it
        if "jax" in sys.modules:
            try:
                jax = sys.modules["jax"]
                info["jax"] = {
                    "version": jax.__version__,
                    "backend": jax.default_backend(),
                    "devices": [str(d) for d in jax.devices()],
                }
            except Exception as e:
                info["jax"] = {"error": repr(e)}
        try:
            from veles_tpu.config import root
            info["config"] = root.__content__()
        except Exception:
            pass
        try:
            from veles_tpu.telemetry.health import monitor
            info["health"] = monitor.state()
        except Exception:
            pass
        try:
            from veles_tpu.telemetry.registry import metrics
            info["metrics"] = metrics.snapshot()
        except Exception:
            pass
        try:
            # the LIVE in-flight request table (trace id, phase, age,
            # blocks held) from every registered scheduler/router —
            # a hang dump must say WHICH requests were stuck, not
            # just where the threads stood
            from veles_tpu.telemetry import reqtrace
            info["requests"] = reqtrace.inflight_table()
        except Exception:
            pass
        try:
            # firing alerts from every live engine: the bundle says
            # what was ALREADY wrong before the crash/hang
            from veles_tpu.telemetry import alerts
            info["alerts"] = alerts.firing_table()
        except Exception:
            pass
        try:
            # the last minutes of tier-0 history for the key serving
            # series (goodput, KV pressure, SLO burn, TTFT p95) from
            # every live store — the LEAD-UP to the hang, not just
            # the moment of death
            from veles_tpu.telemetry import tsdb
            info["history"] = tsdb.bundle_history()
        except Exception:
            pass
        try:
            from veles_tpu.logger import events
            info["events"] = list(events.ring)[-self.max_events:]
        except Exception:
            pass
        info["logs"] = list(self.log_ring)
        try:
            names = {t.ident: t.name for t in threading.enumerate()}
            info["threads"] = {
                "%s-%d" % (names.get(tid, "?"), tid):
                    traceback.format_stack(frame)
                for tid, frame in sys._current_frames().items()}
        except Exception:
            pass
        return info

    def dump(self, reason="manual", extra=None):
        """Write the bundle to ``<dir>/flightrec-<pid>.json``; returns
        the path (None when even the write failed)."""
        try:
            directory = self._resolve_dir()
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory,
                                "flightrec-%d.json" % os.getpid())
            with open(path, "w") as f:
                json.dump(self.bundle(reason, extra=extra), f,
                          default=str, indent=1)
                f.write("\n")
        except Exception as e:
            try:
                log.error("flight-recorder dump failed: %s", e)
            except Exception:
                pass
            return None
        self.dumps.append(path)
        try:
            log.warning("flight-recorder bundle (%s) -> %s", reason,
                        path)
        except Exception:
            pass
        return path

    def state(self):
        """Live-process view for ``GET /debug/state``."""
        from veles_tpu.logger import events
        return {
            "installed": self._installed,
            "dir": self._resolve_dir() if self._installed else None,
            "dumps": list(self.dumps),
            "uptime_s": round(time.time() - self._start, 3),
            "events_buffered": len(events.ring),
            "logs_buffered": len(self.log_ring),
        }


#: process-wide recorder (installed by the CLI entry point)
recorder = FlightRecorder()
