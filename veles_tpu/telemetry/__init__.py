"""veles_tpu.telemetry — unified observability layer.

One process-wide :data:`metrics` registry (counters / gauges /
histograms with bounded reservoirs, labeled series), a span pipeline
over the JSONL :data:`veles_tpu.logger.events` sink, JIT compile
tracking, and two export surfaces:

- Prometheus text exposition at ``GET /metrics`` (served by both
  :mod:`veles_tpu.web_status` and :mod:`veles_tpu.restful_api`);
- Chrome ``trace_event`` JSON from a recorded span log
  (``python -m veles_tpu.telemetry.trace_export run.jsonl trace.json``).

See ``docs/observability.md`` for the metric names and span schema.
"""

from veles_tpu.telemetry.alerts import (  # noqa: F401
    AlertEngine, AlertRule, default_rules, firing_table)
from veles_tpu.telemetry.compile_tracker import (  # noqa: F401
    compile_summary, cost_summary, maybe_profiler_trace, track_jit)
from veles_tpu.telemetry.federation import (  # noqa: F401
    fleet_families, merge_scrapes, parse_prometheus)
from veles_tpu.telemetry.flight_recorder import (  # noqa: F401
    FlightRecorder, recorder)
from veles_tpu.telemetry.health import (  # noqa: F401
    HealthMonitor, health_config, monitor)
from veles_tpu.telemetry.registry import (  # noqa: F401
    Counter, DEFAULT_BUCKETS, Gauge, Histogram, MS_BUCKETS,
    MetricsRegistry, metrics, nearest_rank, render_families_text)
from veles_tpu.telemetry.reqtrace import (  # noqa: F401
    TRACE_HEADER, clean_trace_id, ensure_trace_id, new_trace_id)
from veles_tpu.telemetry.spans import (  # noqa: F401
    iter_spans, next_span_id, span)
from veles_tpu.telemetry.tsdb import (  # noqa: F401
    DEFAULT_TIERS, TimeSeriesStore, bundle_history, history_query)


def enabled():
    """Whether host-side instrumentation (per-unit spans + histograms)
    is on — ``root.common.telemetry.enabled``, default True.  The
    metrics registry itself is always live; this gates only the
    per-run hot-path hooks."""
    from veles_tpu.config import root
    return bool(root.common.telemetry.get("enabled", True))


def unit_timing_summary(top=None):
    """Per-unit run-time digest from the shared histograms —
    ``{unit: {count, sum, mean, p50, p95, ...}}`` sorted by total
    time, optionally truncated to the ``top`` heaviest units."""
    fam = metrics.get("veles_unit_run_seconds")
    if fam is None:
        return {}
    rows = [(child.sum, name, child.summary())
            for (name,), child in fam.children().items()]
    rows.sort(reverse=True)
    if top is not None:
        rows = rows[:top]
    return {name: digest for _, name, digest in rows}
