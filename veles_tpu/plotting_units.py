"""Plotting unit family (rebuild of veles/plotting_units.py:52-822).

Each unit snapshots host-visible training state into a renderer payload
(see :mod:`veles_tpu.plotter`); the matplotlib side lives in
:mod:`veles_tpu.graphics_client`.
"""

import numpy

from veles_tpu.memory import Array
from veles_tpu.plotter import Plotter


def _value_of(obj, attr):
    v = getattr(obj, attr)
    if isinstance(v, Array):
        v.map_read()
        v = v.mem
    if isinstance(v, numpy.ndarray) and v.ndim == 0:
        v = v.item()
    return v


class AccumulatingPlotter(Plotter):
    """Scalar curve over time (ref: plotting_units.py:52
    AccumulatingPlotter): reads ``<obj>.<attr>`` each run and appends to
    the named series."""

    def __init__(self, workflow, obj=None, attr=None, label=None,
                 ylabel="value", **kwargs):
        super(AccumulatingPlotter, self).__init__(workflow, **kwargs)
        self.obj = obj
        self.attr = attr
        self.label = label or attr
        self.ylabel = ylabel
        self.series = []
        self.demand("obj", "attr")

    def payload(self):
        v = _value_of(self.obj, self.attr)
        if v is None:
            return None
        self.series.append(float(v))
        return {"kind": "curve", "ylabel": self.ylabel,
                "series": {self.label: list(self.series)}}


class MatrixPlotter(Plotter):
    """Confusion-matrix heatmap (ref: plotting_units.py MatrixPlotter);
    reads an Array-valued attr (e.g. evaluator.confusion_matrix)."""

    def __init__(self, workflow, obj=None, attr="confusion_matrix",
                 **kwargs):
        super(MatrixPlotter, self).__init__(workflow, **kwargs)
        self.obj = obj
        self.attr = attr
        self.demand("obj")

    def payload(self):
        m = _value_of(self.obj, self.attr)
        if m is None:
            return None
        return {"kind": "matrix", "data": numpy.asarray(m).tolist()}


class ImagePlotter(Plotter):
    """Image grid (ref: plotting_units.py ImagePlotter / Weights2D):
    renders rows of an Array as tiles — weights filters or samples."""

    def __init__(self, workflow, obj=None, attr="weights", limit=16,
                 sample_shape=None, **kwargs):
        super(ImagePlotter, self).__init__(workflow, **kwargs)
        self.obj = obj
        self.attr = attr
        self.limit = limit
        self.sample_shape = sample_shape
        self.demand("obj")

    def payload(self):
        w = _value_of(self.obj, self.attr)
        if w is None:
            return None
        w = numpy.asarray(w, numpy.float32)
        if w.ndim == 4:  # HWIO conv kernels → [O, H, W] mean over I
            tiles = numpy.transpose(w.mean(axis=2), (2, 0, 1))
        elif w.ndim == 2:
            side = self.sample_shape
            if side is None:
                n = int(numpy.sqrt(w.shape[0]))
                side = (n, n) if n * n == w.shape[0] else None
            if side is None:
                return None
            tiles = w.T.reshape(-1, *side)
        else:
            tiles = w.reshape((-1,) + w.shape[-2:])
        tiles = tiles[:self.limit]
        return {"kind": "images", "tiles": tiles.tolist()}


class Histogram(Plotter):
    """Value histogram of one Array (ref: plotting_units.py
    Histogram)."""

    def __init__(self, workflow, obj=None, attr="weights", bins=30,
                 **kwargs):
        super(Histogram, self).__init__(workflow, **kwargs)
        self.obj = obj
        self.attr = attr
        self.bins = bins
        self.demand("obj")

    def payload(self):
        v = _value_of(self.obj, self.attr)
        if v is None:
            return None
        counts, edges = numpy.histogram(
            numpy.asarray(v).ravel(), bins=self.bins)
        return {"kind": "histogram", "counts": counts.tolist(),
                "edges": edges.tolist()}


class MultiHistogram(Plotter):
    """One histogram per forward layer's weights (ref:
    plotting_units.py MultiHistogram)."""

    def __init__(self, workflow, forwards=None, bins=20, **kwargs):
        super(MultiHistogram, self).__init__(workflow, **kwargs)
        self.forwards = forwards
        self.bins = bins
        self.demand("forwards")

    def payload(self):
        hists = {}
        for u in self.forwards:
            arrs = u.param_arrays()
            if "weights" not in arrs:
                continue
            arrs["weights"].map_read()
            counts, edges = numpy.histogram(
                arrs["weights"].mem.ravel(), bins=self.bins)
            hists[u.name] = {"counts": counts.tolist(),
                             "edges": edges.tolist()}
        if not hists:
            return None
        return {"kind": "multi_histogram", "layers": hists}


class TableMaxMin(Plotter):
    """min/max text table over watched Arrays (ref: plotting_units.py
    TableMaxMin)."""

    def __init__(self, workflow, **kwargs):
        super(TableMaxMin, self).__init__(workflow, **kwargs)
        self.watched = []  # (label, obj, attr)

    def watch(self, label, obj, attr):
        self.watched.append((label, obj, attr))
        return self

    def payload(self):
        rows = []
        for label, obj, attr in self.watched:
            v = numpy.asarray(_value_of(obj, attr))
            rows.append([label, float(v.max()), float(v.min())])
        if not rows:
            return None
        return {"kind": "table", "header": ["array", "max", "min"],
                "rows": rows}


class SlaveStats(Plotter):
    """Per-worker state table on the coordinator (ref:
    plotting_units.py SlaveStats + server.py:172-229
    SlaveDescription)."""

    def __init__(self, workflow, coordinator=None, **kwargs):
        super(SlaveStats, self).__init__(workflow, **kwargs)
        self.coordinator = coordinator
        self.demand("coordinator")

    def payload(self):
        rows = [[w.id, w.state, round(w.power, 1), w.jobs_done]
                for w in self.coordinator.workers.values()]
        return {"kind": "table",
                "header": ["worker", "state", "power", "jobs"],
                "rows": rows}
