"""Pickle debugging (rebuild of veles/pickle2.py's debug hooks +
``--debug-pickle``): when a snapshot fails to pickle, walk the object
graph and name exactly which attribute path is unpicklable — the raw
pickle error only names the innermost type."""

import pickle


def _try_pickle(obj):
    try:
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        return None
    except Exception as e:
        return "%s: %s" % (type(e).__name__, e)


def find_unpicklable(obj, path="<root>", max_depth=6, _seen=None):
    """[(attr path, error)] for the deepest unpicklable attributes."""
    _seen = _seen if _seen is not None else set()
    if id(obj) in _seen or max_depth < 0:
        return []
    _seen.add(id(obj))
    err = _try_pickle(obj)
    if err is None:
        return []
    if isinstance(obj, dict):
        items = [("[%r]" % k, v) for k, v in list(obj.items())]
    elif isinstance(obj, (list, tuple, set)):
        items = [("[%d]" % i, v) for i, v in enumerate(obj)]
    elif hasattr(obj, "__getstate__") or hasattr(obj, "__dict__"):
        try:
            state = obj.__getstate__() if hasattr(obj, "__getstate__") \
                else obj.__dict__
        except Exception:
            state = getattr(obj, "__dict__", {})
        if not isinstance(state, dict):
            state = {"<state>": state}
        items = [(".%s" % k, v) for k, v in state.items()]
    else:
        items = []
    found = []
    for name, child in items:
        child_err = _try_pickle(child)
        if child_err is not None:
            deeper = find_unpicklable(child, path + name, max_depth - 1,
                                      _seen)
            found.extend(deeper or [(path + name, child_err)])
    return found or [(path, err)]


def explain_pickle_failure(obj, logger=None):
    """Log (or return) a human-readable diagnosis."""
    rows = find_unpicklable(obj)
    lines = ["unpicklable attribute paths:"] + \
        ["  %s — %s" % (p, e) for p, e in rows[:20]]
    text = "\n".join(lines)
    if logger is not None:
        logger.error(text)
    return text
