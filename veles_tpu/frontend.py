"""Frontend — the web command composer (rebuild of the reference's
``--frontend`` mode, veles/__main__.py:258-332 + web/frontend.html: a
browser form listing every CLI argument; submitting composes the
command line and the waiting process executes it)."""

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from veles_tpu.logger import Logger

_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu frontend</title><style>
 body { font-family: sans-serif; margin: 2em; max-width: 48em; }
 label { display: block; margin-top: .6em; font-weight: bold; }
 .help { color: #666; font-weight: normal; font-size: .9em; }
 input[type=text] { width: 100%; }
 button { margin-top: 1em; padding: .5em 2em; }
</style></head><body>
<h2>Compose a veles_tpu run</h2>
<form method="post" action="/compose">%FIELDS%
<button type="submit">Run</button></form></body></html>
"""


def _fields_from_parser(parser):
    rows = []
    for action in parser._actions:
        if action.dest in ("help",):
            continue
        name = (action.option_strings[-1] if action.option_strings
                else action.dest)
        help_text = (action.help or "").replace("<", "&lt;")
        if action.const is True or getattr(action, "nargs", None) == 0 \
                or type(action).__name__ == "_StoreTrueAction":
            field = ('<label>%s <span class="help">%s</span></label>'
                     '<input type="checkbox" name="%s" value="1">'
                     % (name, help_text, action.dest))
        else:
            field = ('<label>%s <span class="help">%s</span></label>'
                     '<input type="text" name="%s">'
                     % (name, help_text, action.dest))
        rows.append(field)
    return "\n".join(rows)


def compose_argv(parser, form):
    """Browser form dict → argv list (positional workflow/config first,
    then flags)."""
    argv = []
    by_dest = {a.dest: a for a in parser._actions}
    workflow = form.get("workflow", "").strip()
    config = form.get("config", "").strip()
    if workflow:
        argv.append(workflow)
        if config:  # config is positional #2 — meaningless alone
            argv.append(config)
    elif config:
        raise ValueError("a config file needs a workflow file")
    for dest, value in form.items():
        action = by_dest.get(dest)
        if action is None or not action.option_strings \
                or dest in ("workflow", "config",
                            "frontend", "frontend_port"):
            # composing another frontend would recurse into a second
            # bind of the same port
            continue
        value = value.strip()
        if not value:
            continue
        opt = action.option_strings[-1]
        if type(action).__name__ in ("_StoreTrueAction", "_CountAction"):
            argv.append(opt)
        elif type(action).__name__ == "_AppendAction":
            for part in value.split(";;"):
                if part.strip():
                    argv += [opt, part.strip()]
        else:
            argv += [opt, value]
    return argv


class Frontend(Logger):
    """Serves the composer page; :meth:`wait` blocks until a command is
    submitted and returns the composed argv."""

    def __init__(self, parser, port=0, host="127.0.0.1"):
        super(Frontend, self).__init__()
        self.parser = parser
        self._result = None
        self._done = threading.Event()
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = _PAGE.replace(
                    "%FIELDS%",
                    _fields_from_parser(frontend.parser)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length).decode()
                form = dict(urllib.parse.parse_qsl(raw))
                try:
                    argv = compose_argv(frontend.parser, form)
                except ValueError as e:
                    self.send_error(400, str(e))
                    return
                blob = json.dumps({"argv": argv}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)
                frontend._result = argv
                frontend._done.set()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="frontend")
        self._thread.start()
        self.info("frontend on http://%s:%d/ — compose and submit",
                  host, self.port)

    def wait(self, timeout=None):
        if not self._done.wait(timeout):
            return None
        return self._result

    def stop(self):
        self._server.shutdown()
        self._server.server_close()  # release the port for the run
