"""Pickling base + the distributable contract.

Rebuild of veles/distributable.py:

- :class:`Pickleable` (ref: veles/distributable.py:48-134) — snapshotting
  works by pickling live object graphs.  Convention: attributes whose name
  ends with ``_`` are *volatile* (locks, compiled functions, device
  handles, loggers) — they are skipped by ``__getstate__`` and rebuilt by
  ``init_unpickled()`` after load.
- :class:`IDistributable` (ref: veles/distributable.py:222-281) — the
  5-method contract units implement to take part in master–slave style
  data exchange.  On TPU, in-pod gradient sync is ``lax.psum`` inside the
  jitted step (no unit involvement); this contract survives for the
  *elastic DCN layer*: the job-queue coordinator used by ensemble /
  genetics fleets and the elastic data-feeding service.
- :class:`TriviallyDistributable` — no-op defaults.
"""

import threading

from veles_tpu.logger import Logger


def _reconstruct(cls):
    """Unpickling helper: bare instance of the real (unshadowed) class."""
    return cls.__new__(cls)


class Pickleable(Logger):
    """Base for everything snapshot-able.

    Subclasses put volatile state in attributes ending with ``_`` and
    (re)create them inside :meth:`init_unpickled`, which runs both at
    construction and after unpickling (ref: veles/distributable.py:75-119).
    """

    def __init__(self, **kwargs):
        super(Pickleable, self).__init__(**kwargs)
        self.init_unpickled()

    def init_unpickled(self):
        """(Re)build volatile state.  Subclasses must call super()."""
        self._pickle_lock_ = threading.Lock()

    def __getstate__(self):
        state = {}
        for k, v in self.__dict__.items():
            if k.endswith("_"):
                continue
            state[k] = v
        return state

    def __setstate__(self, state):
        links = state.pop("__links__", None)
        self.__dict__.update(state)
        self.init_unpickled()
        if links:
            from veles_tpu.mutable import LinkableAttribute
            for name, src_obj, src_name, two_way in links:
                LinkableAttribute(self, name, (src_obj, src_name),
                                  two_way=two_way)

    def __reduce_ex__(self, protocol):
        # Instances whose class was shadowed by LinkableAttribute pickle
        # through the original class; the link *records* ride along in
        # state (source objects pickle by reference, so identity within a
        # workflow snapshot is preserved by the pickle memo) and the
        # forwarding properties are re-installed in __setstate__.
        from veles_tpu.mutable import unshadow
        cls = unshadow(type(self))
        state = self.__getstate__()
        links = self.__dict__.get("_linked_attrs_")
        if links:
            state["__links__"] = [
                (name, src, sn, tw)
                for name, (src, sn, tw) in links.items()
                # a detached (written-through) one-way link is a plain
                # attribute now; don't resurrect the forwarding
                if name not in self.__dict__]
        return (_reconstruct, (cls,), state)


class IDistributable:
    """The master–slave data-exchange contract
    (ref: veles/distributable.py:222-281).

    ``generate_data_for_slave(slave)`` → picklable job payload;
    ``apply_data_from_master(data)`` consumes it on the worker;
    ``generate_data_for_master()`` → picklable update payload;
    ``apply_data_from_slave(data, slave)`` merges it on the master;
    ``drop_slave(slave)`` undoes in-flight work for a dead worker.
    """

    def generate_data_for_slave(self, slave):
        raise NotImplementedError()

    def generate_data_for_master(self):
        raise NotImplementedError()

    def apply_data_from_master(self, data):
        raise NotImplementedError()

    def apply_data_from_slave(self, data, slave):
        raise NotImplementedError()

    def drop_slave(self, slave):
        raise NotImplementedError()


class Distributable(Pickleable, IDistributable):
    """Pickleable + trivial distributable defaults
    (ref: veles/distributable.py:136-220, 285-302)."""

    #: units that genuinely exchange data override this to True so the
    #: coordinator knows to call the contract methods.
    negotiates_on_connect = False

    def generate_data_for_slave(self, slave):
        return None

    def generate_data_for_master(self):
        return None

    def apply_data_from_master(self, data):
        pass

    def apply_data_from_slave(self, data, slave):
        pass

    def drop_slave(self, slave):
        pass
