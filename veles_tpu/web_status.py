"""Web status dashboard (rebuild of veles/web_status.py:113 +
launcher.py:852-885 status POSTs).

A small tornado service: launchers POST their run status to ``/update``
once a second; browsers read ``/`` (an auto-refreshing table of runs
with per-worker state) and machines read ``/api/runs``.  The
reference's MongoDB-backed log/event viewer maps onto the JSONL event
stream (veles_tpu.logger) — the dashboard links the raw feed instead of
embedding a Mongo browser.

Run standalone:  ``python -m veles_tpu.web_status --port 8090``
"""

import argparse
import json
import threading
import time

from veles_tpu.logger import Logger

try:
    import tornado.ioloop
    import tornado.web
    HAS_TORNADO = True
except ImportError:  # pragma: no cover
    HAS_TORNADO = False


_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu status</title>
<meta http-equiv="refresh" content="2">
<style>
 body { font-family: sans-serif; margin: 2em; }
 table { border-collapse: collapse; }
 td, th { border: 1px solid #999; padding: 4px 10px; }
 th { background: #eee; }
 .dead { color: #999; }
</style></head>
<body><h2>veles_tpu runs</h2>%TABLE%</body></html>
"""


def _render_runs(runs):
    rows = []
    now = time.time()
    for rid, r in sorted(runs.items()):
        age = now - r.get("_received", now)
        cls = ' class="dead"' if age > 10 else ""
        workers = r.get("workers", [])
        wtable = "".join(
            "<br>%s: %s (%.0f jobs)" % (w.get("id"), w.get("state"),
                                        w.get("jobs", 0))
            for w in workers)
        metrics = ", ".join("%s=%s" % (k, v)
                            for k, v in (r.get("metrics") or {}).items())
        rows.append(
            "<tr%s><td>%s</td><td>%s</td><td>%s</td><td>%s</td>"
            "<td>%s</td><td>%.0fs ago</td></tr>"
            % (cls, rid, r.get("workflow", "?"), r.get("mode", "?"),
               metrics, wtable or "-", age))
    return ("<table><tr><th>run</th><th>workflow</th><th>mode</th>"
            "<th>metrics</th><th>workers</th><th>updated</th></tr>"
            + "".join(rows) + "</table>")


class WebStatusServer(Logger):
    """The dashboard service (ref: web_status.py:113)."""

    def __init__(self, port=8090):
        super(WebStatusServer, self).__init__()
        if not HAS_TORNADO:  # pragma: no cover
            raise RuntimeError("tornado is unavailable")
        self.port = port
        self.runs = {}
        server = self

        class Update(tornado.web.RequestHandler):
            def post(self):
                data = json.loads(self.request.body)
                data["_received"] = time.time()
                server.runs[data.get("id", "?")] = data
                self.write({"ok": True})

        class Page(tornado.web.RequestHandler):
            def get(self):
                self.write(_PAGE.replace(
                    "%TABLE%", _render_runs(server.runs)))

        class Api(tornado.web.RequestHandler):
            def get(self):
                self.write({"runs": server.runs})

        self.app = tornado.web.Application([
            (r"/update", Update), (r"/", Page), (r"/api/runs", Api)])
        self._loop = None
        self._thread = None

    def start(self, background=True):
        if not background:
            self.app.listen(self.port)
            tornado.ioloop.IOLoop.current().start()
            return

        started = threading.Event()

        def run():
            import asyncio
            asyncio.set_event_loop(asyncio.new_event_loop())
            self.app.listen(self.port)
            self._loop = tornado.ioloop.IOLoop.current()
            started.set()
            self._loop.start()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="web-status")
        self._thread.start()
        started.wait(5)
        self.info("web status on http://localhost:%d/", self.port)

    def stop(self):
        if self._loop is not None:
            self._loop.add_callback(self._loop.stop)
            self._thread.join(5)


class StatusNotifier(Logger):
    """Launcher-side POST loop (ref: launcher.py:852-885 upload_status):
    periodically reports {id, workflow, mode, metrics, workers} to a
    WebStatusServer's /update."""

    def __init__(self, url, launcher, interval=1.0):
        super(StatusNotifier, self).__init__()
        self.url = url.rstrip("/") + "/update"
        self.launcher = launcher
        self.interval = interval
        self._stop = threading.Event()
        self._thread = None

    def _status(self):
        import os
        launcher = self.launcher
        wf = launcher.workflow
        status = {
            "id": "%s-%d" % (type(wf).__name__, os.getpid()),
            "workflow": getattr(wf, "name", type(wf).__name__),
            "mode": launcher.mode,
            "metrics": wf.gather_results() if wf is not None else {},
        }
        coord = getattr(launcher, "coordinator", None)
        if coord is not None:
            status["workers"] = [
                {"id": w.id, "state": w.state, "power": w.power,
                 "jobs": w.jobs_done} for w in coord.workers.values()]
        return status

    def _post_once(self):
        import urllib.request
        body = json.dumps(self._status(), default=str).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=2).read()

    def run_forever(self):
        while not self._stop.wait(self.interval):
            try:
                self._post_once()
            except Exception as e:
                self.debug("status POST failed: %s", e)

    def start(self):
        self._thread = threading.Thread(target=self.run_forever,
                                        daemon=True, name="status-notify")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            try:  # final state lands even if the loop never fired
                self._post_once()
            except Exception:
                pass
            self._thread.join(3)


def main(argv=None):
    p = argparse.ArgumentParser(prog="veles_tpu.web_status")
    p.add_argument("--port", type=int, default=8090)
    args = p.parse_args(argv)
    WebStatusServer(port=args.port).start(background=False)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
