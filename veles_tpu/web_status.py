"""Web status dashboard (rebuild of veles/web_status.py:113 +
launcher.py:852-885 status POSTs).

A small tornado service: launchers POST their run status to ``/update``
once a second — including the workflow's unit graph and the tail of the
event-span ring; browsers read ``/`` (an auto-refreshing table of runs
with per-worker state), ``/graph/<run>`` (the workflow graph rendered
as layered SVG — the viz.js graph view of the reference's ``web/``,
server-side and dependency-free) and ``/events/<run>`` (a browsable
view of the JSONL event stream, filterable by unit/name/kind — the
reference's Mongo-backed event viewer).  Machines read ``/api/runs``
and scrape ``/metrics`` (the process-wide telemetry registry as
Prometheus text exposition).

Run standalone:  ``python -m veles_tpu.web_status --port 8090``
"""

import argparse
import json
import threading
import time

from veles_tpu.logger import Logger

try:
    import tornado.ioloop
    import tornado.web
    HAS_TORNADO = True
except ImportError:  # pragma: no cover
    HAS_TORNADO = False


_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu status</title>
<meta http-equiv="refresh" content="2">
<style>
 body { font-family: sans-serif; margin: 2em; }
 table { border-collapse: collapse; }
 td, th { border: 1px solid #999; padding: 4px 10px; }
 th { background: #eee; }
 .dead { color: #999; }
</style></head>
<body><h2>veles_tpu runs</h2>
<p><a href="/dashboard">dashboard</a> <a href="/alerts">alerts</a>
 <a href="/metrics">metrics</a> <a href="/debug/state">debug</a></p>
%TABLE%</body></html>
"""


def _render_runs(runs):
    import html
    e = html.escape  # EVERY update-supplied string is attacker input
    rows = []
    now = time.time()
    for rid, r in sorted(runs.items()):
        age = now - r.get("_received", now)
        cls = ' class="dead"' if age > 10 else ""
        workers = r.get("workers", [])
        wtable = "".join(
            "<br>%s: %s (%.0f jobs)" % (e(str(w.get("id"))),
                                        e(str(w.get("state"))),
                                        w.get("jobs", 0))
            for w in workers)
        metrics = ", ".join(
            "%s=%s" % (e(str(k)), e(str(v)))
            for k, v in (r.get("metrics") or {}).items())
        q = html.escape(rid, quote=True)
        links = ('<a href="/graph/%s">graph</a> '
                 '<a href="/events/%s">events</a>' % (q, q))
        rows.append(
            "<tr%s><td>%s</td><td>%s</td><td>%s</td><td>%s</td>"
            "<td>%s</td><td>%.0fs ago</td><td>%s</td></tr>"
            % (cls, e(rid), e(str(r.get("workflow", "?"))),
               e(str(r.get("mode", "?"))), metrics, wtable or "-", age,
               links))
    return ("<table><tr><th>run</th><th>workflow</th><th>mode</th>"
            "<th>metrics</th><th>workers</th><th>updated</th>"
            "<th>views</th></tr>" + "".join(rows) + "</table>")


_GROUP_FILL = {"PLUMBING": "#d9d9d9", "LOADER": "#c6dbef",
               "WORKER": "#c7e9c0", "TRAINER": "#fdd0a2",
               "EVALUATOR": "#fcbba1", "SERVICE": "#dadaeb"}


def _graph_layers(graph):
    """BFS depth from the roots; back edges (Repeater loops) simply
    point upward in the drawing."""
    nodes = graph.get("nodes", [])
    edges = graph.get("edges", [])
    succ = {}
    indeg = {n["id"]: 0 for n in nodes}
    for s, d in edges:
        succ.setdefault(s, []).append(d)
        indeg[d] = indeg.get(d, 0) + 1
    roots = [i for i, d in indeg.items() if d == 0] or \
        [nodes[0]["id"]] if nodes else []
    layer = {}
    frontier = list(roots)
    depth = 0
    while frontier:
        nxt = []
        for i in frontier:
            if i not in layer:
                layer[i] = depth
                nxt.extend(succ.get(i, []))
        frontier = nxt
        depth += 1
    for n in nodes:  # disconnected units go to the bottom
        layer.setdefault(n["id"], depth)
    return layer


def render_graph_svg(graph):
    """Layered SVG of a workflow graph dict (Workflow.graph_dict) —
    dependency-free stand-in for the reference's viz.js DOT render."""
    import html
    nodes = graph.get("nodes", [])
    edges = graph.get("edges", [])
    layer = _graph_layers(graph)
    by_layer = {}
    for n in nodes:
        by_layer.setdefault(layer[n["id"]], []).append(n)
    bw, bh, hgap, vgap = 170, 46, 30, 60
    pos = {}
    width = 40 + max((len(v) for v in by_layer.values()), default=1) \
        * (bw + hgap)
    for ly, members in sorted(by_layer.items()):
        for col, n in enumerate(members):
            pos[n["id"]] = (40 + col * (bw + hgap),
                            30 + ly * (bh + vgap))
    height = 30 + (max(by_layer, default=0) + 1) * (bh + vgap)
    parts = [
        '<svg xmlns="http://www.w3.org/2000/svg" width="%d" height="%d"'
        ' font-family="sans-serif" font-size="12">' % (width, height),
        '<defs><marker id="arr" markerWidth="8" markerHeight="8" '
        'refX="7" refY="3" orient="auto"><path d="M0,0 L7,3 L0,6 z" '
        'fill="#555"/></marker></defs>']
    for s, d in edges:
        if s not in pos or d not in pos:
            continue
        x1, y1 = pos[s][0] + bw / 2, pos[s][1] + bh
        x2, y2 = pos[d][0] + bw / 2, pos[d][1]
        if layer[d] <= layer[s]:  # back edge: loop out the side
            xa = min(pos[s][0], pos[d][0]) - 18
            parts.append(
                '<path d="M%g,%g C%g,%g %g,%g %g,%g" fill="none" '
                'stroke="#b55" stroke-dasharray="4 2" '
                'marker-end="url(#arr)"/>'
                % (x1 - bw / 2, y1 - bh / 2, xa, y1 - bh / 2,
                   xa, y2 + bh / 2, x2 - bw / 2, y2 + bh / 2))
        else:
            parts.append(
                '<line x1="%g" y1="%g" x2="%g" y2="%g" stroke="#555" '
                'marker-end="url(#arr)"/>' % (x1, y1, x2, y2))
    for n in nodes:
        x, y = pos[n["id"]]
        fill = _GROUP_FILL.get(n.get("group"), "#ffffff")
        parts.append(
            '<g><rect x="%g" y="%g" width="%d" height="%d" rx="6" '
            'fill="%s" stroke="#333"/>'
            '<text x="%g" y="%g" text-anchor="middle">%s</text>'
            '<text x="%g" y="%g" text-anchor="middle" fill="#666" '
            'font-size="10">%s</text></g>'
            % (x, y, bw, bh, fill,
               x + bw / 2, y + 19, html.escape(str(n["label"])[:24]),
               x + bw / 2, y + 35, html.escape(str(n["cls"])[:26])))
    parts.append("</svg>")
    return "".join(parts)


def _render_events(run_id, events, unit=None, name=None, kind=None,
                   limit=200):
    """Filterable HTML view of a run's event-span tail (the reference's
    Mongo event browser surface)."""
    import html
    out = []
    for ev in reversed(events):
        if unit and str(ev.get("unit", ev.get("cls", ""))) != unit:
            continue
        if name and name not in str(ev.get("name", "")):
            continue
        if kind and ev.get("kind") != kind:
            continue
        out.append(ev)
        if len(out) >= limit:
            break
    rows = "".join(
        "<tr><td>%s</td><td>%s</td><td>%s</td><td>%s</td><td>%s</td>"
        "</tr>" % (
            time.strftime("%H:%M:%S",
                          time.localtime(ev.get("time", 0)))
            + ".%03d" % (1000 * (ev.get("time", 0) % 1)),
            html.escape(str(ev.get("name", ""))),
            html.escape(str(ev.get("kind", ""))),
            html.escape(str(ev.get("unit", ev.get("cls", "")))),
            html.escape(", ".join(
                "%s=%s" % (k, v) for k, v in sorted(ev.items())
                if k not in ("name", "kind", "unit", "cls", "time",
                             "pid"))))
        for ev in out)
    form = ('<form method="get">unit <input name="unit" value="%s"> '
            'name <input name="name" value="%s"> kind '
            '<select name="kind"><option value="">any</option>'
            '%s</select> <button>filter</button></form>'
            % (html.escape(unit or "", quote=True),
               html.escape(name or "", quote=True),
               "".join('<option%s>%s</option>'
                       % (' selected' if kind == k else '', k)
                       for k in ("begin", "end", "single"))))
    return ("<h2>events — %s</h2>%s<table><tr><th>time</th><th>name"
            "</th><th>kind</th><th>unit</th><th>attrs</th></tr>%s"
            "</table>" % (html.escape(run_id), form, rows))


class WebStatusServer(Logger):
    """The dashboard service (ref: web_status.py:113)."""

    def __init__(self, port=None, host=None):
        super(WebStatusServer, self).__init__()
        if not HAS_TORNADO:  # pragma: no cover
            raise RuntimeError("tornado is unavailable")
        from veles_tpu.config import root
        self.host = host or root.common.web.get("host", "localhost")
        self.port = int(port or root.common.web.get("port", 8090))
        self.runs = {}
        server = self

        class Update(tornado.web.RequestHandler):
            def post(self):
                data = json.loads(self.request.body)
                data["_received"] = time.time()
                server.runs[data.get("id", "?")] = data
                self.write({"ok": True})

        class Page(tornado.web.RequestHandler):
            def get(self):
                self.write(_PAGE.replace(
                    "%TABLE%", _render_runs(server.runs)))

        class Api(tornado.web.RequestHandler):
            def get(self):
                self.write({"runs": server.runs})

        class Graph(tornado.web.RequestHandler):
            def get(self, rid):
                run = server.runs.get(rid)
                if run is None or not run.get("graph"):
                    self.send_error(404)
                    return
                import html as _html
                try:
                    svg = render_graph_svg(run["graph"])
                except Exception:
                    # /update payloads are untrusted: a malformed graph
                    # must not 500 the dashboard
                    self.send_error(400, reason="malformed graph")
                    return
                self.set_header("Content-Type", "text/html")
                self.write("<!DOCTYPE html><html><body><h2>%s — "
                           "workflow graph</h2>%s</body></html>"
                           % (_html.escape(str(run.get("workflow",
                                                       rid))),
                              svg))

        class Metrics(tornado.web.RequestHandler):
            def get(self):
                # the structured-collect path: one text renderer
                # (render_families_text) behind every /metrics tier
                from veles_tpu.telemetry import metrics as registry
                from veles_tpu.telemetry.registry import \
                    render_families_text
                self.set_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.write(render_families_text(
                    registry.collect_families()))

        class Alerts(tornado.web.RequestHandler):
            def get(self):
                # every live engine in this process (replica tiers,
                # an in-process router, standalone engines)
                from veles_tpu.telemetry import alerts
                self.write(json.dumps(
                    {"engines": [e.snapshot()
                                 for e in alerts.live_engines()],
                     "firing": alerts.firing_table()},
                    default=str))
                self.set_header("Content-Type", "application/json")

        class Dashboard(tornado.web.RequestHandler):
            def get(self):
                from veles_tpu.telemetry import alerts, reqtrace
                from veles_tpu.telemetry.dashboard import \
                    render_dashboard_html
                engines = alerts.live_engines()
                merged = {"firing": alerts.firing_table(),
                          "pending": [row for e in engines
                                      for row in e.snapshot()
                                      .get("pending", ())]}
                self.set_header("Content-Type",
                                "text/html; charset=utf-8")
                self.write(render_dashboard_html(
                    "veles_tpu process dashboard",
                    replicas=(), slo=None, alerts=merged,
                    inflight=reqtrace.inflight_table(),
                    note="process-local view: alerts + in-flight "
                         "requests of every engine/scheduler in "
                         "this process (the fleet table lives on "
                         "the router's /dashboard)"))

        class Healthz(tornado.web.RequestHandler):
            def get(self):
                # liveness + health-policy state (503 once halted, so
                # probes/LBs act without parsing the body)
                import os
                from veles_tpu.telemetry.health import monitor
                state = monitor.state()
                if state["status"] == "halted":
                    self.set_status(503)
                self.write({"status": state["status"],
                            "pid": os.getpid(), "health": state})

        class DebugState(tornado.web.RequestHandler):
            def get(self):
                from veles_tpu.logger import events as event_sink
                from veles_tpu.telemetry.flight_recorder import \
                    recorder
                from veles_tpu.telemetry.health import monitor
                self.write(json.dumps({
                    "flightrec": recorder.state(),
                    "health": monitor.state(),
                    "events": list(event_sink.ring)[-100:],
                    "logs": list(recorder.log_ring)[-50:],
                }, default=str))
                self.set_header("Content-Type", "application/json")

        class Events(tornado.web.RequestHandler):
            def get(self, rid):
                run = server.runs.get(rid)
                if run is None:
                    self.send_error(404)
                    return
                try:
                    body = _render_events(
                        rid, run.get("events", []),
                        unit=self.get_argument("unit", None),
                        name=self.get_argument("name", None),
                        kind=self.get_argument("kind", None))
                except Exception:
                    self.send_error(400, reason="malformed events")
                    return
                self.set_header("Content-Type", "text/html")
                self.write("<!DOCTYPE html><html><body>%s</body></html>"
                           % body)

        self.app = tornado.web.Application([
            (r"/update", Update), (r"/", Page), (r"/api/runs", Api),
            (r"/metrics", Metrics), (r"/healthz", Healthz),
            (r"/alerts", Alerts), (r"/dashboard", Dashboard),
            (r"/debug/state", DebugState),
            (r"/graph/(.+)", Graph), (r"/events/(.+)", Events)])
        self._loop = None
        self._thread = None

    def start(self, background=True):
        if not background:
            self.app.listen(self.port, self.host)
            tornado.ioloop.IOLoop.current().start()
            return

        started = threading.Event()

        def run():
            import asyncio
            asyncio.set_event_loop(asyncio.new_event_loop())
            self.app.listen(self.port, self.host)
            self._loop = tornado.ioloop.IOLoop.current()
            started.set()
            self._loop.start()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="web-status")
        self._thread.start()
        started.wait(5)
        self.info("web status on http://localhost:%d/", self.port)

    def stop(self):
        if self._loop is not None:
            self._loop.add_callback(self._loop.stop)
            self._thread.join(5)


class StatusNotifier(Logger):
    """Launcher-side POST loop (ref: launcher.py:852-885 upload_status):
    periodically reports {id, workflow, mode, metrics, workers} to a
    WebStatusServer's /update."""

    def __init__(self, url, launcher, interval=1.0):
        super(StatusNotifier, self).__init__()
        self.url = url.rstrip("/") + "/update"
        self.launcher = launcher
        self.interval = interval
        self._stop = threading.Event()
        self._thread = None

    def _status(self):
        import os
        from veles_tpu.logger import events as event_sink
        launcher = self.launcher
        wf = launcher.workflow
        status = {
            "id": "%s-%d" % (type(wf).__name__, os.getpid()),
            "workflow": getattr(wf, "name", type(wf).__name__),
            "mode": launcher.mode,
            "metrics": wf.gather_results() if wf is not None else {},
        }
        if wf is not None and hasattr(wf, "graph_dict"):
            status["graph"] = wf.graph_dict()
        # tail of the span ring — feeds the dashboard's event viewer
        status["events"] = list(event_sink.ring)[-200:]
        coord = getattr(launcher, "coordinator", None)
        if coord is not None:
            status["workers"] = [
                {"id": w.id, "state": w.state, "power": w.power,
                 "jobs": w.jobs_done} for w in coord.workers.values()]
        return status

    def _post_once(self):
        import urllib.request
        body = json.dumps(self._status(), default=str).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=2).read()

    def run_forever(self):
        while not self._stop.wait(self.interval):
            try:
                self._post_once()
            except Exception as e:
                self.debug("status POST failed: %s", e)

    def start(self):
        self._thread = threading.Thread(target=self.run_forever,
                                        daemon=True, name="status-notify")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            try:  # final state lands even if the loop never fired
                self._post_once()
            except Exception:
                pass
            self._thread.join(3)


def main(argv=None):
    p = argparse.ArgumentParser(prog="veles_tpu.web_status")
    p.add_argument("--port", type=int, default=None,
                   help="default: root.common.web.port (8090)")
    args = p.parse_args(argv)
    WebStatusServer(port=args.port).start(background=False)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
