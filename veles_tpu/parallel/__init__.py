"""Parallelism layer — meshes, shardings, collectives.

This package replaces the reference's entire L5 distributed layer
(veles/server.py, client.py, txzmq/ — the ZeroMQ master–slave star) with
the TPU-native model: SPMD ``pjit`` over a :class:`jax.sharding.Mesh`,
gradient sync as ``lax.psum`` over ICI, cross-slice traffic over DCN, and
a thin elastic coordinator for job-queue workloads (ensemble/genetics).

Modules:

- :mod:`veles_tpu.parallel.mesh`      — mesh construction + axis conventions
- :mod:`veles_tpu.parallel.sharding`  — NamedSharding specs for dp/tp/pp/sp/ep
- :mod:`veles_tpu.parallel.collectives` — psum/all_gather/ppermute wrappers
- :mod:`veles_tpu.parallel.ring`      — ring attention (sequence/context parallel)
- :mod:`veles_tpu.parallel.coordinator` — elastic job-queue service (asyncio)
"""

from veles_tpu.parallel.mesh import MeshConfig, build_mesh  # noqa: F401
