"""Elastic job-queue coordinator — the DCN layer.

Rebuild of the reference's master–slave stack (veles/server.py:659,
client.py, network_common.py, txzmq/): within a pod, gradient sync is
``lax.psum`` inside the jitted step (no coordinator involvement); this
service keeps the *elastic* semantics the reference had across its
ZeroMQ star — workers join/leave anytime, the coordinator hands out jobs
(minibatch index ranges via ``IDistributable``), re-queues work from
dropped workers, and weights distribution by each worker's measured
compute power.  Used by ensemble/genetics fleets and cross-DCN data
serving.

Transport: asyncio TCP with length-prefixed pickle frames + gzip
(replaces Twisted JSON-lines control + txzmq ``vpb``/``vpe`` streamed
pickling, ref: txzmq/connection.py:255-340).  The handshake carries the
workflow checksum (mismatch ⇒ reject, ref: server.py:490-493) and the
worker's compute power (ref: server.py:540-567).

Failure handling (ref: server.py:619-655): per-worker job timers; a job
exceeding ``max(mean + 3σ, job_timeout)`` drops the worker and requeues
its minibatches (``Workflow.drop_slave``).  Blacklisting follows the
reference's *repeat offender* semantics (ref: server.py:383-394): a
worker is banned only after ``blacklist_strikes`` timeouts, a completed
job clears its strikes, and bans expire after ``blacklist_forgive``
seconds (plus an explicit :meth:`Coordinator.forgive`) so a once-slow
worker on a loaded host can rejoin the fleet.

Death detection is two-tier.  A modern :class:`WorkerClient` runs its
job in a thread-pool executor and keeps a **heartbeat** task pinging
the coordinator every ``heartbeat_interval`` seconds even mid-job; a
worker whose pings stop for ``heartbeat_timeout`` seconds while it
holds a job is declared dead LONG before the mean+3σ job watchdog
would fire, its connection is torn down and its in-flight job frame
is requeued to the live fleet (``veles_coordinator_reassigned_total``)
— epoch sample accounting stays exact because ``drop_slave`` refiles
the dead worker's minibatches, honoring the Veles DCN contract
(PAPER.md: the master re-distributes work on worker loss).  Workers
that never ping (legacy/raw peers) keep the job-timeout tier only.
Worker reconnects use capped exponential backoff with jitter
(``veles_coordinator_reconnects_total``) so a restarting coordinator
is not met by a synchronized thundering herd.

Injection points (``coordinator.*`` — :mod:`veles_tpu.faults`, keyed
by worker id) let tier-1 arm dropped heartbeats, hung jobs, slow
dispatches and crashing handlers deterministically.
"""

import asyncio
import collections
import contextlib
import functools
import gzip
import pickle
import random
import struct
import time
import uuid

from veles_tpu import faults
from veles_tpu.logger import Logger

_HDR = struct.Struct("!IB")  # length, flags
_FLAG_GZIP = 1


def _coord_metrics():
    """Fleet-level series in the shared registry (created lazily —
    importing the coordinator must not populate /metrics)."""
    from veles_tpu.telemetry import metrics
    return {
        "workers": metrics.gauge(
            "veles_coordinator_workers",
            "workers currently registered with the coordinator"),
        "dispatched": metrics.counter(
            "veles_coordinator_jobs_dispatched_total",
            "jobs handed to workers"),
        "completed": metrics.counter(
            "veles_coordinator_jobs_completed_total",
            "job updates applied"),
        "dropped": metrics.counter(
            "veles_coordinator_workers_dropped_total",
            "worker sessions dropped (timeouts, disconnects, evictions)"),
        "reassigned": metrics.counter(
            "veles_coordinator_reassigned_total",
            "in-flight job frames requeued to the live fleet after "
            "their worker died (heartbeat/job-timeout/disconnect)"),
        "heartbeat_deaths": metrics.counter(
            "veles_coordinator_heartbeat_deaths_total",
            "workers declared dead because their heartbeats stopped "
            "mid-job"),
        "job_seconds": metrics.histogram(
            "veles_coordinator_job_seconds",
            "job round-trip time (dispatch to update)"),
    }


async def send_frame(writer, obj, compress=True):
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    flags = 0
    if compress and len(blob) > 4096:
        blob = gzip.compress(blob, 1)
        flags |= _FLAG_GZIP
    writer.write(_HDR.pack(len(blob), flags))
    writer.write(blob)
    await writer.drain()


async def recv_frame(reader):
    hdr = await reader.readexactly(_HDR.size)
    length, flags = _HDR.unpack(hdr)
    blob = await reader.readexactly(length)
    if flags & _FLAG_GZIP:
        blob = gzip.decompress(blob)
    return pickle.loads(blob)


class WorkerDescription:
    """ref: veles/server.py:172 SlaveDescription."""

    def __init__(self, wid, power, writer):
        self.id = wid
        self.power = power
        self.writer = writer
        self.state = "WAIT"
        self.jobs_done = 0
        self.job_started = None
        #: trace id of the in-flight job (rides the job frame so the
        #: worker's event stream stitches to the master's in merged
        #: Chrome-trace exports)
        self.trace = None
        #: wall stamp of the last frame received on this session; a
        #: pinging worker that goes silent mid-job is declared dead
        #: at heartbeat_timeout (far before the job watchdog)
        self.last_seen = time.time()
        #: the session has sent at least one ping — only then does
        #: silence mean death (legacy peers never ping; their only
        #: death tier is the job timeout)
        self.heartbeats = False

    def __repr__(self):
        return "<worker %s power=%.1f jobs=%d state=%s>" % (
            self.id, self.power, self.jobs_done, self.state)


class Coordinator(Logger):
    """The coordinator service (ref: veles/server.py:659 Server)."""

    #: rolling window of recent job durations feeding the mean+3σ
    #: watchdog threshold — bounded so a week-long elastic fleet doesn't
    #: accumulate unbounded floats (the reference kept no history at all,
    #: it tracked only per-slave start times, server.py:619-635)
    DURATION_WINDOW = 256

    def __init__(self, workflow, host="127.0.0.1", port=5050,
                 job_timeout=60.0, blacklist_strikes=3,
                 blacklist_forgive=300.0, watchdog_interval=1.0,
                 heartbeat_timeout=10.0):
        super(Coordinator, self).__init__()
        self.workflow = workflow
        self.host, self.port = host, port
        self.job_timeout = job_timeout
        self.watchdog_interval = float(watchdog_interval)
        #: a pinging worker silent this long while holding a job is
        #: dead — its frame requeues to the live fleet (0 disables)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.blacklist_strikes = int(blacklist_strikes)
        self.blacklist_forgive = float(blacklist_forgive)
        self.workers = {}
        self.blacklist = set()
        #: worker id -> {"count", "last_strike", "banned_at"} — ONE
        #: record per offender so strike count, aging, and ban expiry
        #: can't drift apart
        self._offenders = {}
        self.job_durations = collections.deque(maxlen=self.DURATION_WINDOW)
        self._server = None
        self._done = asyncio.Event()
        self._stopping = False
        self._metrics = _coord_metrics()

    @property
    def strikes(self):
        """Read-only view: worker id -> current strike count."""
        return {wid: rec["count"] for wid, rec in self._offenders.items()}

    # -- lifecycle -------------------------------------------------------------

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_connect, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._loop = asyncio.get_running_loop()
        self.info("coordinator listening on %s:%d", self.host, self.port)
        self._watchdog_task = asyncio.ensure_future(self._watchdog())

    def notify_jobs(self):
        """Thread-safe wake for parked workers after jobs arrive from
        OUTSIDE the coordinator's own protocol flow (e.g. a genetics
        fleet submitting the next generation from the optimizer
        thread): without this the wait/resume push has no trigger and
        every worker stays parked."""
        loop = getattr(self, "_loop", None)
        if loop is not None:
            loop.call_soon_threadsafe(
                lambda: asyncio.ensure_future(self._wake_idle()))

    def request_stop(self):
        """Thread-safe run termination: marks the run finished and
        pushes terminate to every connected worker.  ``wait_finished``
        returns and the owner's ``stop()`` drains as usual."""
        loop = getattr(self, "_loop", None)
        if loop is None:
            self._done.set()
            return

        def _finish():
            self._done.set()
            asyncio.ensure_future(self._broadcast_terminate())

        loop.call_soon_threadsafe(_finish)

    async def wait_finished(self):
        await self._done.wait()

    async def stop(self, drain_timeout=10.0):
        # no new jobs from here on (an abort-stop with jobs remaining
        # must not keep dispatching through the drain window)
        self._stopping = True
        await self._broadcast_terminate()
        # wait for sessions to END on their own (worker reads terminate,
        # closes its end, handler unregisters it) rather than closing
        # under them: a server-side close() with an unread frame (e.g. a
        # final "job" request racing the terminate) sends TCP RST, which
        # DISCARDS the terminate buffered toward the worker and strands
        # it in a reconnect loop against a dead server (ref:
        # launcher.py:588-592 "master waits for slaves to drain")
        deadline = time.time() + drain_timeout
        while time.time() < deadline and self.workers:
            await asyncio.sleep(0.05)
        self._watchdog_task.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await self._watchdog_task
        for w in list(self.workers.values()):
            w.writer.close()
        self._server.close()
        # py3.12 wait_closed() blocks until every connection handler AND
        # transport is gone; handlers close their writers in _on_connect's
        # finally, so this terminates — but cap it in case a worker holds
        # its end open across a network partition.
        with contextlib.suppress(asyncio.TimeoutError, TimeoutError):
            await asyncio.wait_for(self._server.wait_closed(), 5.0)

    # -- protocol (ref: server.py:230-254 FSM) ---------------------------------

    async def _on_connect(self, reader, writer):
        peer = writer.get_extra_info("peername")
        try:
            hello = await recv_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionError):
            writer.close()
            return
        checksum = hello.get("checksum")
        if checksum != self.workflow.checksum():
            self.warning("%s: checksum mismatch — rejected", peer)
            await send_frame(writer, {"error": "checksum mismatch"})
            writer.close()
            return
        wid = hello.get("id") or str(uuid.uuid4())[:8]
        self._expire_bans()
        if wid in self.blacklist:
            await send_frame(writer, {"error": "blacklisted"})
            writer.close()
            return
        worker = WorkerDescription(wid, hello.get("power", 1.0), writer)
        stale = self.workers.get(wid)
        if stale is not None:
            # same-id rejoin over a fresh connection (the old one died
            # silently): evict the stale session's registration so its
            # eventual read-error cleanup can't tear down OUR entry, and
            # requeue whatever the dead session had in flight
            self.info("worker %s rejoined — evicting stale session", wid)
            self._drop(stale, requeue=True)
            try:
                stale.writer.close()
            except Exception:
                pass
        self.workers[wid] = worker
        self._metrics["workers"].set(len(self.workers))
        self.info("worker %s joined from %s (power %.1f)", wid, peer,
                  worker.power)
        await send_frame(writer, {"id": wid})
        try:
            await self._serve_worker(worker, reader)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self._drop(worker, requeue=True)
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    async def _finish_session(self, worker, reader):
        """Send terminate and wait (bounded) for the WORKER to close
        first: returning immediately would close a socket that may hold
        an unread frame (the worker's next "job" racing our terminate),
        and close-with-unread-data sends TCP RST — discarding the very
        terminate we buffered (the same race stop()'s drain handles)."""
        await send_frame(worker.writer, {"cmd": "terminate"})
        self._drop(worker, requeue=False)
        try:
            async def drain():
                while True:
                    data = await reader.read(65536)
                    if not data:
                        return
            await asyncio.wait_for(drain(), 5.0)
        except (asyncio.TimeoutError, TimeoutError, ConnectionError,
                OSError):
            pass

    async def _serve_worker(self, worker, reader):
        while True:
            msg = await recv_frame(reader)
            worker.last_seen = time.time()
            cmd = msg.get("cmd")
            if cmd == "ping":
                # liveness only — no reply (the worker's read loop is
                # elsewhere); the stamp above is the whole point
                worker.heartbeats = True
                continue
            if cmd == "job":
                if self.workers.get(worker.id) is not worker:
                    # dropped/evicted session — don't hand a ghost a job
                    # (its in-flight bookkeeping would pollute the live
                    # worker registered under the same id)
                    return
                if self._done.is_set() or self._stopping:
                    await self._finish_session(worker, reader)
                    return
                if self._has_more_jobs():
                    # injected dispatch faults: a delayed/dropped job
                    # frame exercises the worker-side timeout paths
                    if faults.fire("coordinator.dispatch",
                                   key=worker.id):
                        continue
                    job = self.workflow.generate_data_for_slave(worker.id)
                else:
                    # out of fresh jobs but updates still in flight —
                    # the worker parks until the coordinator pushes a
                    # resume (ref NEED_UPDATE postponement,
                    # server.py:369-399; the reference postponed the
                    # deferred rather than polling)
                    worker.state = "IDLE"
                    await send_frame(worker.writer, {"cmd": "wait"})
                    continue
                worker.state = "WORK"
                worker.job_started = time.time()
                self._metrics["dispatched"].inc()
                from veles_tpu.telemetry import next_span_id
                worker.trace = next_span_id()
                self.event("job", "begin", span=worker.trace,
                           trace=worker.trace, worker=worker.id)
                await send_frame(worker.writer,
                                 {"cmd": "job", "data": job,
                                  "trace": worker.trace})
            elif cmd == "update":
                if self._done.is_set() or self._stopping:
                    # run already complete — the straggler's update is
                    # redundant; release it cleanly
                    worker.state = "WAIT"
                    await self._finish_session(worker, reader)
                    return
                if self.workers.get(worker.id) is not worker:
                    # this session was dropped (watchdog timeout or a
                    # same-id rejoin evicted it) and its minibatches were
                    # requeued — applying the late update would double-
                    # count the work when the requeued job completes
                    self.warning("late update from dropped worker %s "
                                 "discarded", worker.id)
                    return
                dt = time.time() - (worker.job_started or time.time())
                self.job_durations.append(dt)
                self._metrics["completed"].inc()
                self._metrics["job_seconds"].observe(dt)
                if worker.trace is not None:
                    self.event("job", "end", span=worker.trace,
                               trace=worker.trace, worker=worker.id,
                               duration=dt)
                    worker.trace = None
                worker.state = "WAIT"
                worker.jobs_done += 1
                # a completed job proves the worker is healthy — clear
                # its timeout strikes (repeat-offender semantics)
                self._offenders.pop(worker.id, None)
                self.workflow.apply_data_from_slave(msg["data"], worker.id)
                if self._finished():
                    self._done.set()
                    # push terminate to EVERYONE now — parked workers
                    # would otherwise only learn at stop(), racing the
                    # server close into a reconnect storm
                    await self._broadcast_terminate()
                else:
                    # applying an update may have freed jobs — wake every
                    # parked worker so it re-requests
                    await self._wake_idle()
            elif cmd == "bye":
                self._drop(worker, requeue=False)
                return

    async def _broadcast_terminate(self):
        for w in list(self.workers.values()):
            try:
                await send_frame(w.writer, {"cmd": "terminate"})
            except Exception:
                pass

    async def _wake_idle(self):
        """Push a resume to every parked worker (replaces the worker-side
        0.2s busy poll); the woken worker re-requests a job and the job
        branch decides job/wait/terminate."""
        for w in list(self.workers.values()):
            if w.state == "IDLE":
                w.state = "WAIT"
                try:
                    await send_frame(w.writer, {"cmd": "resume"})
                except (ConnectionError, OSError):
                    pass

    def _has_more_jobs(self):
        wf = self.workflow
        has = getattr(wf, "has_more_jobs", None)
        return has() if callable(has) else True

    def _finished(self):
        fin = getattr(self.workflow, "all_jobs_done", None)
        return fin() if callable(fin) else False

    # -- failure detection (ref: server.py:619-655) ----------------------------

    def _drop(self, worker, requeue):
        if self.workers.get(worker.id) is not worker:
            # already dropped, or a rejoined session owns the id now —
            # never unregister a registration we don't own
            return
        del self.workers[worker.id]
        self._metrics["dropped"].inc()
        self._metrics["workers"].set(len(self.workers))
        if requeue and not self._done.is_set():
            # the workflow refiles the worker's in-flight minibatches
            # (ref: loader/base.py:679-687 failed_minibatches); the
            # requeued work may unpark idle workers
            if worker.state == "WORK":
                # the dead session held a job frame — its work is now
                # the live fleet's (the Veles DCN reassignment)
                self._metrics["reassigned"].inc()
                if worker.trace is not None:
                    self.event("job", "end", span=worker.trace,
                               trace=worker.trace, worker=worker.id,
                               error="WorkerLost")
                    worker.trace = None
            self.workflow.drop_slave(worker.id)
            self.info("worker %s dropped — work requeued", worker.id)
            asyncio.ensure_future(self._wake_idle())

    def forgive(self, worker_id):
        """Lift a ban (operator override; auto-expiry is
        ``blacklist_forgive`` seconds)."""
        self.blacklist.discard(worker_id)
        self._offenders.pop(worker_id, None)

    def _expire_bans(self):
        # one sweep ages both bans and sub-ban strike records — a
        # churning elastic fleet of ephemeral worker ids must not
        # accumulate offender entries forever
        now = time.time()
        for wid, rec in list(self._offenders.items()):
            stamp = rec["banned_at"] or rec["last_strike"]
            if now - stamp >= self.blacklist_forgive:
                if rec["banned_at"]:
                    self.info("worker %s ban expired — forgiven", wid)
                self.forgive(wid)

    def _timeout_threshold(self):
        """mean + 3·stddev over the rolling duration window, floored at
        ``job_timeout`` (ref: server.py:619-635)."""
        if len(self.job_durations) < 4:
            return self.job_timeout
        mean = sum(self.job_durations) / len(self.job_durations)
        var = sum((d - mean) ** 2 for d in self.job_durations) \
            / len(self.job_durations)
        return max(mean + 3 * var ** 0.5, self.job_timeout)

    async def _watchdog(self):
        while True:
            await asyncio.sleep(self.watchdog_interval)
            self._expire_bans()
            thr = self._timeout_threshold()
            now = time.time()
            for w in list(self.workers.values()):
                if self.heartbeat_timeout > 0 and w.heartbeats \
                        and w.state == "WORK" \
                        and now - w.last_seen > self.heartbeat_timeout:
                    # the heartbeat tier: a pinging worker went silent
                    # mid-job — dead or wedged either way; reassign
                    # its frame NOW instead of waiting out mean+3σ
                    self.warning(
                        "worker %s silent %.1fs mid-job (heartbeat "
                        "timeout %.1fs) — declaring dead, requeueing",
                        w.id, now - w.last_seen,
                        self.heartbeat_timeout)
                    self._metrics["heartbeat_deaths"].inc()
                    self._strike(w.id, now)
                    try:
                        w.writer.close()
                    except Exception:
                        pass
                    self._drop(w, requeue=True)
                    continue
                if w.state == "WORK" and w.job_started \
                        and now - w.job_started > thr:
                    n = self._strike(w.id, now)
                    if n >= self.blacklist_strikes:
                        self.warning(
                            "worker %s exceeded job timeout %.1fs "
                            "(strike %d/%d) — dropping + blacklisting",
                            w.id, thr, n, self.blacklist_strikes)
                    else:
                        self.warning(
                            "worker %s exceeded job timeout %.1fs "
                            "(strike %d/%d) — dropping, may rejoin",
                            w.id, thr, n, self.blacklist_strikes)
                    try:
                        w.writer.close()
                    except Exception:
                        pass
                    self._drop(w, requeue=True)

    def _strike(self, wid, now):
        """Record one timeout strike against ``wid`` (repeat-offender
        semantics); the Nth strike bans.  Returns the new count."""
        rec = self._offenders.setdefault(
            wid, {"count": 0, "last_strike": now, "banned_at": None})
        rec["count"] += 1
        rec["last_strike"] = now
        if rec["count"] >= self.blacklist_strikes:
            self.blacklist.add(wid)
            rec["banned_at"] = now
        return rec["count"]


class RejectedError(ConnectionError):
    """The coordinator actively refused this worker (blacklisted,
    checksum mismatch, …) — retrying cannot help, unlike transport
    failures."""


class WorkerClient(Logger):
    """Reconnecting worker (ref: veles/client.py Client).

    Jobs execute in a thread-pool executor so the event loop stays
    live mid-job: a heartbeat task pings the coordinator every
    ``heartbeat_interval`` seconds (0 disables), which is what lets
    the master tell "working on a long job" from "dead" without
    waiting out the mean+3σ job watchdog.  Transport losses reconnect
    with capped exponential backoff plus jitter (base
    ``reconnect_delay``, cap ``reconnect_cap``, budget
    ``max_reconnects``) — a coordinator restart must not be greeted by
    every worker at once."""

    def __init__(self, workflow, address, power=None, worker_id=None,
                 reconnect_delay=1.0, max_reconnects=10,
                 reconnect_cap=30.0, heartbeat_interval=1.0):
        super(WorkerClient, self).__init__()
        self.workflow = workflow
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.power = power
        self.worker_id = worker_id
        self.reconnect_delay = float(reconnect_delay)
        self.reconnect_cap = float(reconnect_cap)
        self.max_reconnects = max_reconnects
        self.heartbeat_interval = float(heartbeat_interval)

    def _backoff(self, attempt):
        """Delay before reconnect ``attempt`` (1-based): exponential
        from ``reconnect_delay``, capped at ``reconnect_cap``, with
        half-window jitter so a fleet's retries decorrelate."""
        base = min(self.reconnect_cap,
                   self.reconnect_delay * (2 ** (attempt - 1)))
        return base * (0.5 + 0.5 * random.random())

    async def run(self):
        from veles_tpu.telemetry import metrics
        reconnects = metrics.counter(
            "veles_coordinator_reconnects_total",
            "worker reconnect attempts after a lost coordinator "
            "connection (exponential backoff with jitter)")
        attempts = 0
        while True:
            try:
                await self._session()
                return
            except RejectedError:
                # a protocol-level refusal is permanent — reconnecting
                # would hammer the coordinator and mask the real reason
                raise
            except (ConnectionError, asyncio.IncompleteReadError, OSError):
                attempts += 1
                if attempts > self.max_reconnects:
                    raise ConnectionError(
                        "coordinator unreachable after %d reconnect "
                        "attempts" % self.max_reconnects)
                delay = self._backoff(attempts)
                reconnects.inc()
                self.warning("connection lost — reconnect %d/%d in "
                             "%.2fs", attempts, self.max_reconnects,
                             delay)
                await asyncio.sleep(delay)

    async def _heartbeat(self, writer):
        """Ping until cancelled: the coordinator reads liveness off
        these even while the executor grinds a long job.  A ``drop``
        fault here simulates the half-dead worker (socket open, a job
        in hand, nothing flowing) heartbeat death detection exists
        for."""
        try:
            while True:
                await asyncio.sleep(self.heartbeat_interval)
                if faults.fire("coordinator.worker.heartbeat",
                               key=self.worker_id):
                    continue
                await send_frame(writer, {"cmd": "ping"})
        except (ConnectionError, OSError):
            return  # session teardown races us; the main loop reports

    def _run_job(self, data, on_done):
        """Executor-side job body: the injected-fault hook first (a
        ``hang`` here is a wedged worker whose heartbeats — or their
        injected absence — decide its fate), then the real work."""
        faults.fire("coordinator.worker.job", key=self.worker_id)
        self.workflow.do_job(data, None, on_done)

    async def _session(self):
        import concurrent.futures
        reader, writer = await asyncio.open_connection(self.host, self.port)
        heartbeat = None
        # one dedicated job thread per worker: jobs of THIS worker
        # stay serialized (the pre-executor contract) while the event
        # loop — heartbeats, other in-process workers — keeps running
        executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="worker-job")
        try:
            await send_frame(writer, {
                "checksum": self.workflow.checksum(),
                "power": self.power if self.power is not None else 1.0,
                "id": self.worker_id,
            })
            reply = await recv_frame(reader)
            if "error" in reply:
                raise RejectedError(reply["error"])
            self.worker_id = reply["id"]
            self.info("joined as worker %s", self.worker_id)
            if self.heartbeat_interval > 0:
                heartbeat = asyncio.ensure_future(
                    self._heartbeat(writer))
            while True:
                await send_frame(writer, {"cmd": "job"})
                msg = await recv_frame(reader)
                cmd = msg.get("cmd")
                while cmd == "wait":
                    # park until the coordinator pushes resume/terminate
                    # (no busy poll — the coordinator wakes us the moment
                    # an update frees jobs or the run completes)
                    msg = await recv_frame(reader)
                    cmd = msg.get("cmd")
                if cmd == "terminate":
                    return
                if cmd == "resume":
                    continue
                update = {}

                def on_done(data):
                    update["data"] = data

                # the master's trace id brackets the local execution so
                # merged master+worker span logs stitch per job
                trace = msg.get("trace")
                self.event("job.work", "begin", span=trace,
                           trace=trace, worker=self.worker_id)
                t0 = time.time()
                try:
                    # the executor keeps the EVENT LOOP free while the
                    # job grinds: heartbeats (and other workers in the
                    # same process) keep flowing
                    await asyncio.get_running_loop().run_in_executor(
                        executor, functools.partial(
                            self._run_job, msg["data"], on_done))
                finally:
                    self.event("job.work", "end", span=trace,
                               trace=trace, worker=self.worker_id,
                               duration=time.time() - t0)
                await send_frame(writer, {"cmd": "update",
                                          "data": update.get("data")})
        finally:
            if heartbeat is not None:
                heartbeat.cancel()
                with contextlib.suppress(
                        asyncio.CancelledError, Exception):
                    await heartbeat
            # wait=False: a hung job must not wedge session teardown
            # (its thread ends with the hang; the session is gone)
            executor.shutdown(wait=False)
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()


def serve_master(launcher):
    """Blocking coordinator entry used by the Launcher."""
    host, _, port = (launcher._listen or ":5050").rpartition(":")

    async def _main():
        coord = Coordinator(launcher.workflow, host or "0.0.0.0",
                            int(port or 5050))
        launcher.coordinator = coord  # SlaveStats / web status read it
        await coord.start()
        await coord.wait_finished()
        await coord.stop()

    asyncio.run(_main())


def serve_worker(launcher):
    """Blocking worker entry used by the Launcher."""
    power = launcher.device.compute_power() / 1e9 if launcher.device \
        else 1.0

    async def _main():
        client = WorkerClient(launcher.workflow,
                              launcher._master_address, power=power)
        await client.run()

    asyncio.run(_main())
