"""Device mesh construction and axis conventions.

The framework's canonical mesh axes (SURVEY.md §2.3 "TPU mapping"):

- ``dp``  — data parallel (gradient psum; replaces the reference's whole
  master–slave weight-delta exchange, veles/server.py + client.py),
- ``fsdp`` — data parallel with sharded parameters (reduce_scatter /
  all_gather riding ICI),
- ``tp``  — tensor parallel (activation/weight sharding inside a layer),
- ``pp``  — pipeline parallel (stage dimension),
- ``sp``  — sequence/context parallel (ring attention axis),
- ``ep``  — expert parallel.

The reference had only elastic DP over ZeroMQ; here every strategy is a
mesh axis and XLA inserts the collectives.
"""

import math
from dataclasses import dataclass, field

import jax
import numpy
from jax.sharding import Mesh

#: canonical axis order — outer (slowest, DCN-friendly) to inner
#: (fastest, ICI-friendly).  dp outermost so cross-slice traffic is the
#: infrequent gradient reduction; tp/sp innermost so their chatty
#: collectives ride ICI.
AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass
class MeshConfig:
    """Declarative mesh spec: axis name -> size; -1 = absorb remaining
    devices."""

    axes: dict = field(default_factory=lambda: {"dp": -1})

    def resolve(self, n_devices):
        sizes = dict(self.axes)
        fixed = math.prod(s for s in sizes.values() if s > 0)
        wild = [a for a, s in sizes.items() if s <= 0]
        if len(wild) > 1:
            raise ValueError("at most one -1 axis: %s" % wild)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    "%d devices not divisible by fixed axes %s"
                    % (n_devices, sizes))
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError("mesh %s != %d devices" % (sizes, n_devices))
        return {a: sizes[a] for a in AXIS_ORDER if a in sizes} | {
            a: s for a, s in sizes.items() if a not in AXIS_ORDER}


def build_mesh(axes, devices=None):
    """Build a :class:`jax.sharding.Mesh` from ``{axis: size}``.

    Axes are laid out in :data:`AXIS_ORDER` so inner (chatty) axes map to
    physically adjacent devices.  ``-1`` absorbs the remaining devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = MeshConfig(dict(axes)).resolve(len(devices))
    names = tuple(sizes)
    shape = tuple(sizes[a] for a in names)
    dev_array = numpy.array(devices).reshape(shape)
    return Mesh(dev_array, names)


def single_device_mesh(axis="dp", device=None):
    """A 1-element mesh so the same pjit code path runs on one chip."""
    dev = device or jax.devices()[0]
    return Mesh(numpy.array([dev]), (axis,))
