"""Multi-host SPMD entry (SURVEY.md §2.3 "Multi-host / DCN execution").

The reference scaled across hosts with its elastic ZeroMQ star (one
process per slave, veles/server.py); the TPU-native equivalent is gang
SPMD: every host process joins one ``jax.distributed`` coordination
service, the mesh spans ALL processes' devices, and XLA routes
collectives over ICI within a slice and DCN across slices.  The elastic
DCN job-queue layer (veles_tpu.parallel.coordinator) remains the
between-gang tier (ensemble/genetics fleets, parameter-server mode).

Wire-up: call :func:`initialize` before the first JAX use — explicitly,
via the ``VELES_TPU_COORDINATOR`` / ``VELES_TPU_NUM_PROCESSES`` /
``VELES_TPU_PROCESS_ID`` environment (the Launcher does this), or rely
on the TPU pod metadata auto-detection jax.distributed already does on
Cloud TPU VMs.
"""

import os


def _is_initialized(jax):
    """``jax.distributed.is_initialized`` where it exists (jax >=
    0.4.35-ish); on older jax fall back to probing the distributed
    client's global state.  NOTE: enabling this path used to trip
    nondeterministic glibc heap corruption in the XLA:CPU span step
    (same-process CLI training after other jax work) — root-caused to
    donated buffers aliasing host numpy memory and fixed in
    memory.py's donatable_devmem(); see ROUND6_NOTES.md."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        try:
            return bool(probe())
        except Exception:  # pragma: no cover - defensive
            return False
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except Exception:  # pragma: no cover - very old jax
        return False


def initialize(coordinator_address=None, num_processes=None,
               process_id=None, local_device_ids=None, auto=False):
    """Join the jax.distributed coordination service.

    Configuration sources, in order: explicit args, the
    ``VELES_TPU_COORDINATOR`` / ``VELES_TPU_NUM_PROCESSES`` /
    ``VELES_TPU_PROCESS_ID`` environment, or — only with ``auto=True`` —
    jax.distributed's own cluster auto-detection (Cloud TPU pod
    metadata, SLURM, …).  With nothing configured and ``auto`` unset
    this is a single-process no-op.

    Returns (process_id, num_processes) after initialization.  Safe to
    call when already initialized (no-op).
    """
    import jax

    if _is_initialized(jax):
        # idempotent: report the live gang's coordinates
        return jax.process_index(), jax.process_count()

    coordinator_address = coordinator_address or os.environ.get(
        "VELES_TPU_COORDINATOR")
    if num_processes is None and "VELES_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["VELES_TPU_NUM_PROCESSES"])
    if process_id is None and "VELES_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["VELES_TPU_PROCESS_ID"])

    if num_processes in (None, 1) and coordinator_address is None \
            and not auto:
        return 0, 1  # single process — nothing to join
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    return jax.process_index(), jax.process_count()


def global_mesh(axes):
    """A mesh over ALL processes' devices (jax.devices() is global after
    :func:`initialize`)."""
    import jax

    from veles_tpu.parallel.mesh import build_mesh
    return build_mesh(axes, devices=jax.devices())


def global_put(host_array, mesh, spec):
    """Build a global jax.Array from per-process host data (every
    process passes the SAME full ``host_array`` — the replicated-input
    convention; each reference slave also held a full dataset copy)."""
    from jax.sharding import NamedSharding

    from veles_tpu.parallel.sharding import put
    return put(host_array, NamedSharding(mesh, spec))


def process_allgather(value):
    """Host-level allgather of small per-process python values (worker
    status/metrics aggregation without the coordinator tier)."""
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(value)


def sync_global_devices(tag):
    """Barrier across processes (checkpoint rendezvous etc.)."""
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(tag)
