"""Pipeline parallelism — GPipe-style stage execution over the ``pp``
mesh axis (SURVEY.md §2.3: the reference had no cross-device model
partitioning; here a stage is a mesh-axis shard and activations hop
stage→stage over ICI via ``ppermute``).

The partitioner stacks per-stage parameters along a leading stage dim
(sharded over ``pp``); the scheduler is the classic bubble loop: with S
stages and M microbatches, steps t = 0..S+M-2, stage s processes
microbatch t-s, activations ppermute forward each step."""

import functools

import jax
import jax.numpy as jnp


def _pvary(x, axis_name):
    """Mark a fresh (axis-invariant) value as varying over axis_name —
    pcast on new JAX, pvary on older releases, identity on jax
    versions that predate replication tracking (nothing to mark)."""
    try:
        return jax.lax.pcast(x, axis_name, to="varying")
    except (AttributeError, TypeError):
        pass
    try:
        return jax.lax.pvary(x, (axis_name,))
    except AttributeError:
        return x


def split_stages(n_layers, n_stages):
    """Contiguous layer→stage assignment: [n_stages] lists of layer
    indices, balanced within ±1 (the first n_layers %% n_stages stages
    take one extra layer)."""
    if n_stages > n_layers:
        raise ValueError("more stages (%d) than layers (%d)"
                         % (n_stages, n_layers))
    base, extra = divmod(n_layers, n_stages)
    out, start = [], 0
    for s in range(n_stages):
        size = base + (1 if s < extra else 0)
        out.append(list(range(start, start + size)))
        start += size
    return out


def stack_stage_params(per_stage_params):
    """[stage][...pytree...] → one pytree with a leading stage dim
    (shard it over ``pp``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def gpipe_apply(stage_fn, stacked_params, microbatches, axis_name):
    """Run the pipeline under ``shard_map``.

    - ``stage_fn(params, h) -> h`` — one stage's forward (all stages
      must map activations of identical shape/dtype, the classic GPipe
      constraint);
    - ``stacked_params`` — per-device slice of the stage-stacked params
      (leading dim 1 under shard_map);
    - ``microbatches`` — [M, mb, ...] the SAME on every device
      (replicated input).

    Returns [M, mb, ...] final-stage outputs (valid on the last stage;
    callers broadcast/psum as needed — the wrapper below does)."""
    n = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    params = jax.tree.map(lambda p: p[0], stacked_params)
    steps = m + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    # carries derive FROM the input so they inherit its varying axes
    # (batch may be dp-sharded on a pp×dp mesh), then get marked
    # varying over the stage axis the loop rotates them around
    h0 = _pvary(microbatches[0] * 0, axis_name)
    outputs0 = _pvary(microbatches * 0, axis_name)
    microbatches = _pvary(microbatches, axis_name)

    def body(carry, t):
        recv, outputs = carry
        # stage 0 injects microbatch t; later stages consume the hop
        mb_idx = jnp.clip(t, 0, m - 1)
        h_in = jnp.where(stage == 0, microbatches[mb_idx], recv)
        h_out = stage_fn(params, h_in)
        # the last stage banks its result for microbatch t-(n-1)
        out_idx = jnp.clip(t - (n - 1), 0, m - 1)
        valid = (stage == n - 1) & (t >= n - 1)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(valid, h_out, outputs[out_idx]), out_idx, 0)
        recv = jax.lax.ppermute(h_out, axis_name, perm)
        return (recv, outputs), None

    (recv, outputs), _ = jax.lax.scan(
        body, (h0, outputs0), jnp.arange(steps))
    # broadcast the last stage's outputs to every device so the result
    # is replicated (one psum over pp; zeros elsewhere)
    outputs = jnp.where(stage == n - 1, outputs, 0)
    return jax.lax.psum(outputs, axis_name)


def gpipe_train(mesh, stage_fn, stacked_params, x, n_micro,
                axis="pp", batch_axes=None):
    """Trace-friendly GPipe: runs INSIDE a jitted (and differentiable)
    program — no device_put, shardings applied as constraints.  The
    trainer (models/gd.py) calls this from its fused step, so the
    pipeline's backward (the transposed ppermute schedule) and the
    solver update live in the same XLA program.

    - ``stacked_params``: pytree with leading stage dim (traced
      values); constrained to P(axis) here;
    - ``x``: [batch, ...] activations entering stage 0;
    - ``batch_axes``: data-parallel mesh axes the batch dim is sharded
      over (pp×dp composition — each dp slice runs its own bubble
      schedule).

    Returns [batch, ...] outputs of the last stage, replicated over
    ``axis``."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5 keeps it in experimental
        from jax.experimental.shard_map import shard_map

    if x.shape[0] % n_micro:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (x.shape[0], n_micro))
    micro = x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])
    stage_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    stacked = jax.lax.with_sharding_constraint(
        stacked_params,
        jax.tree.map(lambda s: NamedSharding(mesh, s), stage_spec))
    mb_spec = P(None, tuple(batch_axes)) if batch_axes else P()
    micro = jax.lax.with_sharding_constraint(
        micro, NamedSharding(mesh, mb_spec))
    fn = shard_map(
        functools.partial(gpipe_apply, stage_fn, axis_name=axis),
        mesh=mesh, in_specs=(stage_spec, mb_spec), out_specs=mb_spec)
    out = fn(stacked, micro)
    return out.reshape((x.shape[0],) + out.shape[2:])


def pipeline_forward(mesh, stage_fn, per_stage_params, x, n_micro,
                     axis="pp", batch_axes=None):
    """Convenience wrapper: stack params, microbatch x [batch, ...],
    run the GPipe loop, return [batch, ...] outputs (replicated over
    ``pp``).

    ``batch_axes`` composes the pipeline with data parallelism: each
    microbatch's sample dim shards over those mesh axes (e.g.
    ``("dp",)`` on a pp×dp mesh — every dp slice runs its own bubble
    schedule on its batch shard, stages still hop over ``pp``)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5 keeps it in experimental
        from jax.experimental.shard_map import shard_map

    if len(per_stage_params) != mesh.shape[axis]:
        raise ValueError(
            "%d stages != %s axis size %d — each mesh position holds "
            "exactly one stage (group layers with split_stages first)"
            % (len(per_stage_params), axis, mesh.shape[axis]))
    if x.shape[0] % n_micro:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (x.shape[0], n_micro))
    micro = x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])
    stacked = stack_stage_params(per_stage_params)
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis)))
    stage_spec = jax.tree.map(lambda _: P(axis), stacked)
    mb_spec = P(None, tuple(batch_axes)) if batch_axes else P()

    fn = shard_map(
        functools.partial(gpipe_apply, stage_fn, axis_name=axis),
        mesh=mesh, in_specs=(stage_spec, mb_spec), out_specs=mb_spec)
    out = fn(stacked, micro)
    return out.reshape((x.shape[0],) + out.shape[2:])
