"""Sharding specs — how workflow state maps onto the mesh.

Replaces the reference's master–slave weight-delta exchange
(veles/server.py + client.py over ZeroMQ) with SPMD sharding: annotate
the train step's inputs/outputs with NamedShardings and XLA inserts the
collectives (gradient psum over ``dp``, all-gathers over ``tp``/``fsdp``)
on ICI.

The default policy:

- minibatch tensors: batch axis over ``dp`` (and ``fsdp`` if present);
- FC weights [in, out]: ``tp`` over the output features (Megatron
  column-parallel) and ``fsdp`` over the input features — parameters and
  solver state are sharded, XLA all-gathers them for the forward and
  reduce-scatters the gradients (ZeRO-3 semantics via sharding
  propagation);
- conv kernels [h, w, i, o]: ``tp`` over output channels;
- expert-major MoE params (``expert_*``, [E, ...]): ``ep`` over the
  expert dimension (models/moe.py);
- solver state: same layout as its parameter (scalars replicated);
- everything else replicated.
"""

from jax.sharding import NamedSharding, PartitionSpec as P


def is_cross_process(sharding):
    """True when the sharding includes devices of other processes."""
    import jax
    return any(d.process_index != jax.process_index()
               for d in sharding.device_set)


def put(value, sharding):
    """``jax.device_put`` that also works when the sharding spans other
    hosts' devices (multi-host gangs): the local fast path is a plain
    device_put; the cross-host path re-assembles the global array from
    host data, each process contributing the shards its devices own
    (every process holds the same host value — the framework's
    replicated-input convention)."""
    import jax

    if isinstance(value, jax.Array) and value.sharding == sharding:
        return value  # already placed
    if not is_cross_process(sharding):
        return jax.device_put(value, sharding)
    from veles_tpu.memory import Array
    host = Array._fetch_host(value)  # handles global source arrays too
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx])


def _axis_size(mesh, name):
    return mesh.shape[name] if name in mesh.axis_names else 1


def batch_spec(mesh, ndim, dim0=None, seq_dim1=None):
    """Batch-axis spec.  When ``dim0`` (the static batch size) is given,
    raises a clear error if it doesn't divide over the data axes instead
    of letting device_put fail mid-training.

    ``seq_dim1`` marks dim 1 as a SEQUENCE dim of that length: on a
    mesh with an ``sp`` axis it shards over sp (the ring-attention
    layout).  Only the caller knows dim 1's meaning — a [batch, seq]
    token minibatch sp-shards, an MSE target's feature dim must not —
    so sp sharding is strictly opt-in via this parameter."""
    axes = [a for a in ("dp", "fsdp")
            if _axis_size(mesh, a) > 1]
    sp = _axis_size(mesh, "sp")
    shard_seq = sp > 1 and ndim >= 2 and seq_dim1 is not None
    if shard_seq and seq_dim1 % sp:
        raise ValueError(
            "sequence length %d is not divisible by the sp extent %d — "
            "pick a sequence length that is a multiple of it"
            % (seq_dim1, sp))
    if not axes and not shard_seq:
        return P(*([None] * ndim))
    total = 1
    for a in axes:
        total *= mesh.shape[a]
    if dim0 is not None and dim0 % total:
        raise ValueError(
            "minibatch size %d is not divisible by the data-parallel "
            "extent %d (mesh axes %s) — pick a minibatch_size that is a "
            "multiple of it" % (dim0, total, axes))
    spec = [tuple(axes) if axes else None] + [None] * (ndim - 1)
    if shard_seq:
        spec[1] = "sp"
    return P(*spec)


def param_spec(mesh, name, shape):
    """Sharding spec for one parameter tensor by convention."""
    tp = _axis_size(mesh, "tp")
    fsdp = _axis_size(mesh, "fsdp")
    ep = _axis_size(mesh, "ep")
    ndim = len(shape)
    spec = [None] * ndim
    if name.startswith("expert_") and ep > 1 and ndim >= 2 \
            and shape[0] % ep == 0:
        # expert-major MoE parameters: the expert dimension lives on
        # ``ep`` (models/moe.py — expert einsums run expert-local, the
        # combine psums over ep)
        spec[0] = "ep"
    if ndim >= 1 and tp > 1 and shape[-1] % tp == 0:
        spec[-1] = "tp"
    if fsdp > 1:
        # ZeRO-style: shard the largest remaining axis over fsdp
        for ax in range(ndim - 1, -1, -1):
            if spec[ax] is None and shape[ax] % fsdp == 0 \
                    and shape[ax] >= fsdp:
                spec[ax] = "fsdp"
                break
    if all(s is None for s in spec):
        return P()
    return P(*spec)


def param_sharding(mesh, name, shape):
    return NamedSharding(mesh, param_spec(mesh, name, shape))


def replicated(mesh):
    return NamedSharding(mesh, P())


def batch_sharding(mesh, ndim, dim0=None, seq_dim1=None):
    return NamedSharding(mesh, batch_spec(mesh, ndim, dim0, seq_dim1))
