"""AcceleratedUnit — the jit compilation layer.

Rebuild of veles/accelerated_units.py (130-866).  The reference bound
per-backend methods (``ocl_run``/``cuda_run``/``numpy_run``), assembled
kernel source with Jinja2 and cached compiled binaries per device.  The
TPU-native design replaces all of that with *tracing*:

- An accelerated unit declares the attributes it READS and WRITES and
  implements one **pure** :meth:`AcceleratedUnit.step` over jax values.
  There is no per-backend code: the same traced function runs on TPU and
  on (virtual multi-device) CPU, which is what made the reference keep
  three kernel dialects in sync.
- ``Array`` objects are the SSA registers between units: ``link_attrs``
  aliases an attribute to the upstream unit's Array, so the segment
  compiler can key the dataflow by Array identity.
- Consecutive accelerated units **fuse into one jitted XLA program**
  (:class:`FusedSegment`) — the north-star design decision (SURVEY.md §7):
  one device dispatch per workflow segment per minibatch instead of the
  reference's per-unit kernel launches.  Read-write (state) Arrays are
  donated so parameters update in place in HBM.
- The binary cache (ref: accelerated_units.py:605-673 tar.gz of PTX) is
  XLA's persistent compilation cache, enabled once per process.

Standalone (unfused) accelerated units still jit their own step; eager
mode (``root.common.engine.eager = True``) skips jit entirely for
debugging, like the reference's numpy fallback path.
"""

import jax

from veles_tpu.config import root
from veles_tpu.memory import Array
from veles_tpu.units import Unit
from veles_tpu.workflow import Workflow

_compile_cache_enabled = [False]


def enable_persistent_compile_cache():
    """XLA's on-disk compile cache — replaces the reference's tar.gz
    kernel binary cache (ref: veles/accelerated_units.py:605-673)."""
    if _compile_cache_enabled[0]:
        return
    cache_dir = root.common.dirs.get("cache")
    if cache_dir:
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            _compile_cache_enabled[0] = True
        except Exception:
            pass


class AcceleratedUnit(Unit):
    """A unit whose run() is a pure traced function over its declared
    attributes (ref: veles/accelerated_units.py:130).

    Subclasses declare::

        READS  = ("input", "weights", "bias")   # consumed attrs (Arrays)
        WRITES = ("output", "weights", "bias")  # produced attrs

    and implement :meth:`step`.  An attr in both READS and WRITES is
    *state* — its buffer is donated to the compiled program so updates
    happen in place in HBM.
    """

    hide_from_registry = True

    READS = ()
    WRITES = ()
    #: units that override run() or mutate host state per-iteration set
    #: this False so fuse() leaves them standalone
    FUSABLE = True

    def __init__(self, workflow, **kwargs):
        super(AcceleratedUnit, self).__init__(workflow, **kwargs)
        self.device = None

    def init_unpickled(self):
        super(AcceleratedUnit, self).init_unpickled()
        self._jit_step_ = None
        self._segment_ = None

    @property
    def reads(self):
        return self.READS

    @property
    def writes(self):
        return self.WRITES

    # -- subclass contract ---------------------------------------------------

    def step(self, **tensors):
        """Pure function: ``{read attr: jax value} -> {write attr: jax
        value}``.  Traced under jit; no side effects, no Python branches
        on tensor values."""
        raise NotImplementedError(
            "%s must implement step()" % type(self).__name__)

    # -- lifecycle -----------------------------------------------------------

    def initialize(self, device=None, **kwargs):
        super(AcceleratedUnit, self).initialize(**kwargs)
        if device is not None:
            self.device = device
        enable_persistent_compile_cache()
        for attr in set(self.reads) | set(self.writes):
            arr = getattr(self, attr, None)
            if isinstance(arr, Array):
                arr.initialize(self.device)

    def run(self):
        if self._segment_ is not None:
            self._segment_.run_for(self)
        else:
            self._run_standalone()

    # -- standalone execution ------------------------------------------------

    def _gather(self):
        tensors = {}
        for attr in self.reads:
            val = getattr(self, attr)
            tensors[attr] = val.devmem if isinstance(val, Array) else val
        return tensors

    def _scatter(self, outputs):
        for attr, val in outputs.items():
            target = getattr(self, attr, None)
            if isinstance(target, Array):
                target.devmem = val
            else:
                setattr(self, attr, val)

    def _run_standalone(self):
        if root.common.engine.get("eager"):
            self._scatter(self.step(**self._gather()))
            return
        if self._jit_step_ is None:
            def stepper(donated, held):
                return self.step(**donated, **held)

            from veles_tpu.telemetry import track_jit
            self._jit_step_ = track_jit(
                "accel.%s" % type(self).__name__,
                jax.jit(stepper, donate_argnums=(0,)))
        tensors = self._gather()
        wset = set(self.writes)
        # state buffers are DONATED — hand over donation-safe ones
        # (host-aliased CPU buffers get detached, memory.py)
        donated = {}
        for a, t in tensors.items():
            if a not in wset:
                continue
            arr = getattr(self, a)
            donated[a] = arr.donatable_devmem() \
                if isinstance(arr, Array) else t
        held = {a: t for a, t in tensors.items() if a not in wset}
        self._scatter(self._jit_step_(donated, held))


class FusedSegment:
    """A maximal chain of accelerated units compiled into ONE jitted XLA
    program (the TPU answer to per-unit kernel dispatch, SURVEY.md §7).

    The scheduler still walks every unit's gates; the first member to run
    in an iteration executes the whole fused program, and the remaining
    members' run() calls are satisfied from it.
    """

    def __init__(self, units):
        self.units = list(units)
        self._pending = set()
        self._fallback = False
        self._jit = None
        # stable Array registry: id -> (index, array)
        self._arrays = []
        self._plan = None

    # -- planning ------------------------------------------------------------

    def _array_key(self, arr, registry):
        key = registry.get(id(arr))
        if key is None:
            key = len(self._arrays)
            registry[id(arr)] = key
            self._arrays.append(arr)
        return key

    def plan(self):
        """Resolve each unit's attrs to Array slots; classify slots into
        donated (read+written) / held (read-only) inputs and outputs."""
        registry = {}
        unit_io = []
        written = set()
        read_before_write = set()
        all_written = set()
        for u in self.units:
            ins, outs = {}, {}
            for attr in u.reads:
                arr = getattr(u, attr)
                if not isinstance(arr, Array):
                    raise TypeError("%s.%s is not an Array" % (u, attr))
                k = self._array_key(arr, registry)
                ins[attr] = k
                if k not in written:
                    read_before_write.add(k)
            for attr in u.writes:
                arr = getattr(u, attr)
                if not isinstance(arr, Array):
                    raise TypeError("%s.%s is not an Array" % (u, attr))
                k = self._array_key(arr, registry)
                outs[attr] = k
                written.add(k)
                all_written.add(k)
            unit_io.append((u, ins, outs))
        donated = sorted(read_before_write & all_written)
        held = sorted(read_before_write - all_written)
        outputs = sorted(all_written)
        self._plan = (unit_io, donated, held, outputs)
        return self._plan

    def _fused(self, donated_vals, held_vals):
        unit_io, donated, held, outputs = self._plan
        env = dict(zip(donated, donated_vals))
        env.update(zip(held, held_vals))
        for u, ins, outs in unit_io:
            tensors = {a: env[k] for a, k in ins.items()}
            result = u.step(**tensors)
            for a, k in outs.items():
                env[k] = result[a]
        return tuple(env[k] for k in outputs)

    # -- execution -----------------------------------------------------------

    def _execute(self):
        if self._plan is None:
            self.plan()
        _, donated, held, outputs = self._plan
        held_vals = tuple(self._arrays[k].devmem for k in held)
        if root.common.engine.get("eager"):
            donated_vals = tuple(self._arrays[k].devmem
                                 for k in donated)
            results = self._fused(donated_vals, held_vals)
        else:
            # the fused program donates the state slots — detach any
            # host-aliased buffer first (memory.py, ROUND6_NOTES.md)
            donated_vals = tuple(self._arrays[k].donatable_devmem()
                                 for k in donated)
            if self._jit is None:
                from veles_tpu.telemetry import track_jit
                self._jit = track_jit(
                    "fused:%s" % self.units[0].name,
                    jax.jit(self._fused, donate_argnums=(0,)))
            results = self._jit(donated_vals, held_vals)
        for k, v in zip(outputs, results):
            self._arrays[k].devmem = v

    def run_for(self, unit):
        """Called from each member's run().  The scheduler already
        enforces gates, so a member whose gate_skip/gate_block is set
        never arrives here — an iteration where any member's gate is
        engaged must therefore run per-unit, not fused."""
        if unit not in self._pending:
            # new iteration: either the previous one drained, or it never
            # did because a gate_block cut propagation mid-chain
            expected = {u for u in self.units
                        if not u.gate_skip and not u.gate_block}
            self._fallback = expected != set(self.units)
            if not self._fallback:
                self._execute()
            self._pending = expected
        self._pending.discard(unit)
        if self._fallback:
            unit._run_standalone()

    def __repr__(self):
        return "<FusedSegment %s>" % [u.name for u in self.units]


class AcceleratedWorkflow(Workflow):
    """Workflow owning a device; fuses accelerated-unit chains at
    initialize time (ref: veles/accelerated_units.py:827)."""

    hide_from_registry = True

    def __init__(self, workflow=None, **kwargs):
        super(AcceleratedWorkflow, self).__init__(workflow, **kwargs)
        self.device = None

    def init_unpickled(self):
        super(AcceleratedWorkflow, self).init_unpickled()
        self._segments_ = []

    def initialize(self, device=None, **kwargs):
        if device is None:
            from veles_tpu.backends import Device
            device = Device()
        self.device = device
        super(AcceleratedWorkflow, self).initialize(device=device, **kwargs)
        # always clear stale segment bindings from a previous initialize
        # (graph may have been rewired, or fusion turned off)
        self._segments_ = []
        for u in self.units:
            if isinstance(u, AcceleratedUnit):
                u._segment_ = None
        if root.common.engine.get("fuse", True):
            self.fuse()

    def fuse(self):
        """Find maximal SINGLE-ENTRY convex regions of accelerated units
        and compile each into a :class:`FusedSegment`.

        A segment grows from an entry unit by repeatedly absorbing any
        fusable unit ALL of whose predecessors are already members —
        this admits fan-out and fan-in (InputJoiner diamonds) inside
        the segment, not just linear chains, while keeping execution
        correct: only the entry has edges from outside, so when the
        scheduler releases the entry every member's inputs exist, and
        the grow order is a topological order of the region (each
        member was added after all its predecessors)."""
        self._segments_ = []

        def fusable(u):
            return isinstance(u, AcceleratedUnit) and u.FUSABLE

        accel = [u for u in self.units if fusable(u)]
        accel_set = set(accel)
        # visit candidate entries in TOPOLOGICAL order of the fusable
        # subgraph — unit insertion order is not reliable (a unit
        # linked before its predecessor was created would otherwise
        # become an entry and strand that predecessor unfused).  Kahn;
        # cycle remainders (only possible via gated loops) keep
        # insertion order.
        indeg = {u: sum(1 for p in u.links_from if p in accel_set)
                 for u in accel}
        ready = [u for u in accel if indeg[u] == 0]
        topo = []
        while ready:
            u = ready.pop(0)
            topo.append(u)
            for v in u.links_to:
                if v in indeg:
                    indeg[v] -= 1
                    if indeg[v] == 0:
                        ready.append(v)
        done = set(topo)
        topo += [u for u in accel if u not in done]
        in_segment = set()

        for entry in topo:
            if entry in in_segment:
                continue
            members = [entry]
            member_set = {entry}
            grown = True
            while grown:
                grown = False
                # scan the frontier: successors of members whose every
                # predecessor is already inside
                for m in list(members):
                    for v in m.links_to:
                        if (v in accel_set and v not in member_set
                                and v not in in_segment
                                and v.links_from
                                and all(p in member_set
                                        for p in v.links_from)):
                            members.append(v)
                            member_set.add(v)
                            grown = True
            if len(members) > 1:
                in_segment |= member_set
                seg = FusedSegment(members)
                for member in members:
                    member._segment_ = seg
                self._segments_.append(seg)
        if self._segments_:
            self.debug("fused %d segment(s): %s", len(self._segments_),
                       self._segments_)
        return self._segments_

    @property
    def computing_power(self):
        """Device rating for the elastic coordinator handshake
        (ref: veles/accelerated_units.py:843-858)."""
        return self.device.compute_power() if self.device else 0.0


class DeviceBenchmark(AcceleratedUnit):
    """Unit exposing the GEMM roofline probe in-graph
    (ref: veles/accelerated_units.py:706)."""

    FUSABLE = False  # no step(); runs host-side at initialize

    def __init__(self, workflow, **kwargs):
        super(DeviceBenchmark, self).__init__(workflow, **kwargs)
        self.computing_power = 0.0

    def initialize(self, device=None, **kwargs):
        super(DeviceBenchmark, self).initialize(device=device, **kwargs)
        if self.device is not None:
            self.computing_power = self.device.compute_power()

    def run(self):
        pass
