"""Plotter base — live-visualization units.

Rebuild of veles/plotter.py:48 + graphics_server.py:73: plotting units
run inside the training graph, but rendering happens OUT of process —
the unit's ``run()`` only snapshots host-side state into a small
picklable *payload* which the :class:`~veles_tpu.graphics_server.
GraphicsServer` fans out over ZMQ PUB to any number of
:mod:`~veles_tpu.graphics_client` processes (matplotlib lives there,
never in the training process).

Redesign note: the reference pickled the entire live Plotter unit
through the PUB socket (plotter.py DataStreamer); payloads here are
plain dicts — cheaper to serialize, and the client needs no access to
framework classes.
"""

import time

from veles_tpu.units import Unit


class Plotter(Unit):
    """Base plotting unit (ref: veles/plotter.py:48).

    Subclasses implement :meth:`payload` returning a picklable dict with
    at least ``kind`` (the client's renderer key).  ``run()`` publishes
    it through the launcher's graphics server when one is live; the
    latest payload is always kept on ``last_payload`` (tests and the
    direct-render path read it).
    """

    VIEW_GROUP = "PLOTTER"

    def __init__(self, workflow, name=None, collect=False, **kwargs):
        super(Plotter, self).__init__(workflow, name=name, **kwargs)
        self.last_payload = None
        #: build payloads even without a publisher (tests / direct
        #: rendering); off by default — payload() may sync the device,
        #: which must not happen on the hot loop of a plain run
        self.collect = collect

    @property
    def graphics_server(self):
        # walk up through nested workflows to the launcher
        launcher = getattr(self._workflow, "launcher", None)
        return getattr(launcher, "graphics_server", None)

    def payload(self):
        raise NotImplementedError()

    def run(self):
        server = self.graphics_server
        if server is None and not self.collect:
            return
        data = self.payload()
        if data is None:
            return
        data.setdefault("name", self.name)
        data.setdefault("time", time.time())
        self.last_payload = data
        if server is not None:
            server.enqueue(data)
