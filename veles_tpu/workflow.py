"""Workflow — the unit container and scheduler.

Rebuild of veles/workflow.py:87-1051.  A Workflow owns a set of Units plus
``start_point``/``end_point``, initializes them in dependency order (with
re-queue on unsatisfied demands), and runs the graph to completion with a
deterministic worklist scheduler (see the design note in
:mod:`veles_tpu.units`).

A Workflow is itself a Unit, so workflows nest (ref: workflow.py:87).  The
top-level workflow's parent is the Launcher, which supplies the runtime
mode (standalone / coordinator / worker) and the device.
"""

import hashlib
import inspect
import time
from collections import deque

from veles_tpu.mutable import Bool
from veles_tpu.plumbing import StartPoint, EndPoint
from veles_tpu.result_provider import IResultProvider
from veles_tpu.units import MissingDemand, Unit


class NoMoreJobs(Exception):
    """Raised by the data feed when the job queue is exhausted
    (ref: veles/workflow.py:500-502)."""


class Workflow(Unit):
    """Directed graph of units with start/end points
    (ref: veles/workflow.py:87)."""

    hide_from_registry = True

    def __init__(self, workflow=None, name=None, **kwargs):
        self.units = []          # before super() — add_ref may fire early
        self._sched_queue_ = deque()
        super(Workflow, self).__init__(workflow, name=name, **kwargs)
        self.stopped = Bool(False, "stopped")
        self.start_point = StartPoint(self)
        self.end_point = EndPoint(self)
        self._run_time = 0.0

    def init_unpickled(self):
        super(Workflow, self).init_unpickled()
        self._sched_queue_ = deque()
        # volatile (often a launcher closure) — never snapshotted
        self.run_is_finished_callback_ = None

    # -- membership ---------------------------------------------------------

    def add_ref(self, unit):
        if unit is not self and unit not in self.units:
            self.units.append(unit)

    def del_ref(self, unit):
        if unit in self.units:
            self.units.remove(unit)

    def __iter__(self):
        return iter(self.units)

    def __len__(self):
        return len(self.units)

    def __getitem__(self, key):
        """Units by name or index (ref: workflow.py:~250)."""
        if isinstance(key, str):
            for u in self.units:
                if u.name == key:
                    return u
            raise KeyError(key)
        return self.units[key]

    # -- mode flags (delegated to the launcher) ----------------------------

    @property
    def launcher(self):
        w = self._workflow
        while isinstance(w, Workflow):
            w = w._workflow
        return w

    @property
    def is_standalone(self):
        l = self.launcher
        return l.mode == "standalone" if l is not None else True

    @property
    def is_master(self):
        l = self.launcher
        return l.mode == "master" if l is not None else False

    @property
    def is_slave(self):
        l = self.launcher
        return l.mode == "slave" if l is not None else False

    # -- initialization (ref: workflow.py:303-341) --------------------------

    def initialize(self, **kwargs):
        """Initialize all units in dependency order: a unit raising
        :class:`MissingDemand` is re-queued until its supplier has
        initialized; no-progress passes raise."""
        self.verify_demands()
        pending = list(self.units)
        while pending:
            requeue, last_err = [], None
            for u in pending:
                try:
                    u.initialize(**kwargs)
                except MissingDemand as e:
                    requeue.append(u)
                    last_err = e
            if len(requeue) == len(pending):
                raise last_err
            pending = requeue
        self._is_initialized = True

    # -- scheduling ---------------------------------------------------------

    def schedule(self, unit, src):
        self._sched_queue_.append((unit, src))

    def run(self):
        """Run the graph to completion (one full wave from start_point
        until end_point fires or the queue drains)
        (ref: workflow.py:351-377).  The wave is one paired span in
        the event log; ``root.common.trace.profiler_dir`` additionally
        wraps it in a ``jax.profiler`` device trace."""
        from veles_tpu.telemetry import (
            maybe_profiler_trace, metrics, next_span_id)
        self.stopped.set(False)
        self._sched_queue_.clear()
        t0 = time.time()
        span_id = next_span_id()
        self.event("workflow run", "begin", workflow=self.name,
                   span=span_id)
        try:
            with maybe_profiler_trace():
                self.schedule(self.start_point, None)
                while self._sched_queue_ and not self.stopped:
                    unit, src = self._sched_queue_.popleft()
                    unit._check_gate_and_run(src)
        finally:
            dt = time.time() - t0
            self._run_time += dt
            self.event("workflow run", "end", workflow=self.name,
                       span=span_id, duration=dt)
            metrics.histogram(
                "veles_workflow_run_seconds",
                "wall time of one full workflow wave",
                ("workflow",)).labels(self.name).observe(dt)
        if self.run_is_finished_callback_ is not None:
            self.run_is_finished_callback_()

    def on_workflow_finished(self):
        self.stopped.set(True)

    def stop(self):
        self.stopped.set(True)
        for u in self.units:
            u.stop()

    # -- master–worker aggregation (IDistributable over all units,
    #    ref: workflow.py:478-558) — used by the elastic DCN layer ---------

    def _unit_keys(self):
        # unique payload keys: units may share a default name, and
        # construction order is deterministic on both ends
        return {u: "%s#%d" % (u.name, i)
                for i, u in enumerate(self.units)}

    def generate_data_for_slave(self, slave=None):
        return {k: u.generate_data_for_slave(slave)
                for u, k in self._unit_keys().items()
                if u.negotiates_on_connect}

    def apply_data_from_master(self, data):
        for u, k in self._unit_keys().items():
            if u.negotiates_on_connect and k in data:
                u.apply_data_from_master(data[k])

    def generate_data_for_master(self):
        return {k: u.generate_data_for_master()
                for u, k in self._unit_keys().items()
                if u.negotiates_on_connect}

    def apply_data_from_slave(self, data, slave=None):
        for u, k in self._unit_keys().items():
            if u.negotiates_on_connect and k in data:
                u.apply_data_from_slave(data[k], slave)

    def drop_slave(self, slave=None):
        for u in self.units:
            if u.negotiates_on_connect:
                u.drop_slave(slave)

    def do_job(self, data, update, callback):
        """Worker-side: apply job payload, run the local graph, send the
        update back (ref: workflow.py:558)."""
        self.apply_data_from_master(data)
        if update is not None:
            self.apply_data_from_master(update)
        self.run()
        callback(self.generate_data_for_master())

    def has_more_jobs(self):
        """Coordinator-side: keep serving until a unit (the Decision)
        declares the workflow finished (ref NoMoreJobs flow:
        veles/workflow.py:500-502)."""
        return not bool(self.stopped)

    def all_jobs_done(self):
        return bool(self.stopped)

    # -- results (ref: workflow.py:827-849) ---------------------------------

    def gather_results(self):
        metrics = {}
        for u in self.units:
            if isinstance(u, IResultProvider):
                metrics.update(u.get_metric_values() or {})
        return metrics

    # -- introspection ------------------------------------------------------

    def package_export(self, path, batch=None):
        """Export the forward chain as an inference package
        (ref: veles/workflow.py:868-975; consumed by
        veles_tpu.package_export.load_package and the C++ runner in
        runtime/).  Requires ``self.forwards`` + ``self.loader`` (the
        StandardWorkflow shape)."""
        from veles_tpu.package_export import export_package
        forwards = getattr(self, "forwards", None)
        if not forwards:
            raise ValueError("%s has no forward chain to export" % self)
        loader = getattr(self, "loader", None)
        if loader is None or not bool(loader.minibatch_data):
            raise ValueError(
                "%s has no initialized loader — package_export needs its "
                "minibatch shape/dtype" % self)
        in_shape = list(loader.minibatch_data.shape)
        if batch is not None:
            in_shape[0] = int(batch)
        return export_package(
            forwards, path, in_shape,
            input_dtype=loader.minibatch_data.mem.dtype,
            name=type(self).__name__, checksum=self.checksum())

    def checksum(self):
        """Stable digest of the workflow's defining source — coordinator /
        worker handshakes compare it (ref: workflow.py:852)."""
        from veles_tpu.mutable import unshadow
        cls = unshadow(type(self))
        try:
            src = inspect.getsource(cls)
        except (OSError, TypeError):
            src = cls.__qualname__
        return hashlib.sha256(src.encode()).hexdigest()

    _GROUP_COLORS = {
        "PLUMBING": "lightgrey", "LOADER": "lightblue",
        "WORKER": "palegreen", "TRAINER": "gold",
        "EVALUATOR": "plum", "SERVICE": "white",
    }

    def graph_dict(self):
        """The unit graph as plain data — {nodes: [{id,label,cls,group}],
        edges: [[src,dst]]} — consumed by the DOT export below and the
        web dashboard's SVG renderer (ref: the viz.js graph view,
        veles/web_status.py:66-112 + web/)."""
        index = {u: i for i, u in enumerate(self.units)}
        nodes = [{"id": i, "label": u.name, "cls": type(u).__name__,
                  "group": u.view_group} for u, i in index.items()]
        edges = [[index[u], index[dst]] for u in self.units
                 for dst in u.links_to if dst in index]
        return {"name": self.name, "nodes": nodes, "edges": edges}

    def generate_graph(self, filename=None):
        """Graphviz DOT export of the unit graph
        (ref: workflow.py:628)."""
        g = self.graph_dict()
        lines = ["digraph %s {" % type(self).__name__.replace(" ", "_"),
                 "  rankdir=TB;"]
        for n in g["nodes"]:
            color = self._GROUP_COLORS.get(n["group"], "white")
            lines.append('  u%d [label="%s", style=filled, fillcolor=%s];'
                         % (n["id"], n["label"], color))
        for src, dst in g["edges"]:
            lines.append("  u%d -> u%d;" % (src, dst))
        lines.append("}")
        dot = "\n".join(lines)
        if filename:
            with open(filename, "w") as f:
                f.write(dot)
        return dot

    def print_stats(self, top=5):
        """Top-N per-unit run-time table (ref: workflow.py:788-825),
        with per-run p50/p95 and cumulative gate-wait from the shared
        telemetry histograms when instrumentation is on."""
        from veles_tpu.telemetry import metrics
        stats = sorted(((u.timers["run"], u.timers["runs"], u.name)
                        for u in self.units), reverse=True)[:top]
        total = self._run_time or sum(s[0] for s in stats) or 1e-9
        run_fam = metrics.get("veles_unit_run_seconds")
        wait_fam = metrics.get("veles_unit_gate_wait_seconds")
        self.info("---- unit run-time stats (total %.2fs) ----", total)
        for t, n, name in stats:
            extra = ""
            hist = run_fam.children().get((name,)) if run_fam else None
            if hist is not None and hist.count:
                p50 = hist.percentile(0.50)
                p95 = hist.percentile(0.95)
                extra = "  p50 %.4fs  p95 %.4fs" % (p50, p95)
            wait = wait_fam.children().get((name,)) if wait_fam \
                else None
            if wait is not None and wait.count:
                extra += "  gate-wait %.3fs" % wait.sum
            self.info("  %-30s %8.3fs  %6d runs  %5.1f%%%s",
                      name, t, n, 100.0 * t / total, extra)
        from veles_tpu.telemetry.health import monitor
        health_line = monitor.summary_line()
        if health_line:
            self.info("  %s", health_line)
        return stats
