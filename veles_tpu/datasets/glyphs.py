"""Procedural handwritten-digit surrogate (MNIST-shaped: 28x28 gray).

Each digit class is a stroke skeleton (polyline segments in a unit
box).  Every rendered sample applies a random affine warp (rotation,
anisotropic scale, shear, translation), per-endpoint jitter and a
random stroke width, then draws intensity as a soft distance field —
max over segments of exp(-d^2 / 2*sigma^2) — plus pixel noise.  The
deformation ranges are tuned so the reference MnistSimple MLP
(784-100-10, SGD) lands in the low-percent validation-error band the
real MNIST sits in, rather than memorizing rigid templates.

All geometry is vectorized numpy; 70k samples render in seconds.
"""

import numpy

# stroke skeletons per digit, unit box (x right, y DOWN), as polylines
_POLYLINES = {
    0: [[(.3, .15), (.7, .15), (.82, .5), (.7, .85), (.3, .85),
         (.18, .5), (.3, .15)]],
    1: [[(.35, .3), (.55, .15), (.55, .85)]],
    2: [[(.25, .3), (.4, .15), (.65, .15), (.75, .35), (.25, .85),
         (.75, .85)]],
    3: [[(.25, .2), (.65, .15), (.72, .33), (.5, .48), (.72, .65),
         (.65, .85), (.25, .8)]],
    4: [[(.6, .85), (.6, .15), (.22, .6), (.8, .6)]],
    5: [[(.7, .15), (.3, .15), (.28, .45), (.65, .45), (.74, .65),
         (.6, .85), (.28, .8)]],
    6: [[(.65, .15), (.35, .35), (.25, .62), (.4, .85), (.62, .82),
         (.72, .62), (.55, .48), (.3, .55)]],
    7: [[(.25, .15), (.75, .15), (.45, .85)]],
    8: [[(.5, .15), (.7, .25), (.62, .46), (.38, .52), (.3, .72),
         (.5, .85), (.7, .72), (.62, .52), (.38, .46), (.3, .25),
         (.5, .15)]],
    9: [[(.7, .4), (.5, .5), (.3, .4), (.32, .2), (.55, .13),
         (.7, .25), (.66, .6), (.5, .85)]],
}


def _segments(cls):
    segs = []
    for line in _POLYLINES[cls]:
        pts = numpy.asarray(line, numpy.float32)
        segs.append(numpy.concatenate([pts[:-1], pts[1:]], axis=1))
    return numpy.concatenate(segs, axis=0)  # [S, 4] = x1 y1 x2 y2


_SEGS = [_segments(c) for c in range(10)]
_MAX_S = max(len(s) for s in _SEGS)
#: [10, S, 4], zero-padded; padded segments carry weight 0
_SEG_BANK = numpy.zeros((10, _MAX_S, 4), numpy.float32)
_SEG_MASK = numpy.zeros((10, _MAX_S), numpy.float32)
for _c, _s in enumerate(_SEGS):
    _SEG_BANK[_c, :len(_s)] = _s
    _SEG_MASK[_c, :len(_s)] = 1.0


def render_digits(n, seed=0, size=28, noise=0.14, jitter=0.024,
                  max_rot=0.42, shear=0.28, seg_dropout=0.03,
                  distractor_p=0.12, _chunk=4096):
    """Render ``n`` digit samples; returns (images [n,size,size] f32 in
    [0,1], labels [n] int64).

    ``seg_dropout`` (random missing stroke pieces) and ``distractor_p``
    (a random extra stroke) give the task *irreducible* ambiguity so a
    large training set can't drive the error to zero — without them a
    60k corpus was memorizable to 0.13% where real MNIST sits at
    ~1.5%."""
    if n > _chunk:
        # the [chunk, S, size*size] distance field is the memory peak —
        # render in slabs
        parts = [render_digits(min(_chunk, n - i), seed + 7919 * i,
                               size, noise, jitter, max_rot, shear,
                               seg_dropout, distractor_p)
                 for i in range(0, n, _chunk)]
        return (numpy.concatenate([p[0] for p in parts]),
                numpy.concatenate([p[1] for p in parts]))
    rng = numpy.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    segs = _SEG_BANK[labels].copy()          # [n, S, 4]
    mask = _SEG_MASK[labels].copy()          # [n, S]

    # per-endpoint jitter (bends strokes sample-to-sample)
    segs += rng.normal(scale=jitter, size=segs.shape).astype(
        numpy.float32)

    # stroke-piece dropout: erase random segments (pen skips)
    mask = mask * (rng.random(mask.shape) >= seg_dropout)

    # distractor stroke: one random short segment (pen smudge)
    has_extra = rng.random(n) < distractor_p
    p0 = rng.uniform(0.15, 0.85, (n, 2)).astype(numpy.float32)
    p1 = p0 + rng.uniform(-0.3, 0.3, (n, 2)).astype(numpy.float32)
    extra = numpy.concatenate([p0, p1], axis=1)[:, None, :]  # [n,1,4]
    segs = numpy.concatenate([segs, extra], axis=1)
    mask = numpy.concatenate(
        [mask, has_extra[:, None].astype(numpy.float32)], axis=1)

    # random affine about the glyph center
    theta = rng.uniform(-max_rot, max_rot, n)
    sx = rng.uniform(0.72, 1.12, n)
    sy = rng.uniform(0.72, 1.12, n)
    sh = rng.uniform(-shear, shear, n)
    tx = rng.uniform(-0.09, 0.09, n)
    ty = rng.uniform(-0.09, 0.09, n)
    ct, st = numpy.cos(theta), numpy.sin(theta)
    # A = R(theta) @ Shear @ diag(sx, sy)
    a00 = ct * sx + (-st) * sh * sx
    a01 = (-st) * sy
    a10 = st * sx + ct * sh * sx
    a11 = ct * sy
    for off in (0, 2):  # both endpoints
        x = segs[:, :, off] - 0.5
        y = segs[:, :, off + 1] - 0.5
        segs[:, :, off] = (a00[:, None] * x + a01[:, None] * y
                           + 0.5 + tx[:, None])
        segs[:, :, off + 1] = (a10[:, None] * x + a11[:, None] * y
                               + 0.5 + ty[:, None])

    # soft distance field on the pixel grid
    px = (numpy.arange(size, dtype=numpy.float32) + 0.5) / size
    gx, gy = numpy.meshgrid(px, px)          # [size, size], gy rows
    gx = gx.ravel()[None, None, :]           # [1, 1, P]
    gy = gy.ravel()[None, None, :]
    x1 = segs[:, :, 0:1]
    y1 = segs[:, :, 1:2]
    dx = segs[:, :, 2:3] - x1
    dy = segs[:, :, 3:4] - y1
    seg_len2 = numpy.maximum(dx * dx + dy * dy, 1e-8)
    t = ((gx - x1) * dx + (gy - y1) * dy) / seg_len2
    t = numpy.clip(t, 0.0, 1.0)
    d2 = (gx - (x1 + t * dx)) ** 2 + (gy - (y1 + t * dy)) ** 2
    sigma = rng.uniform(0.022, 0.042, n).astype(numpy.float32)
    field = numpy.exp(-d2 / (2 * sigma[:, None, None] ** 2))
    field = field * mask[:, :, None]
    img = field.max(axis=1).reshape(n, size, size)

    img += rng.normal(scale=noise, size=img.shape)
    return numpy.clip(img, 0.0, 1.0).astype(numpy.float32), labels
