"""Procedural GTZAN surrogate — ten synthetic "genres" of music-like
audio (zero-egress stand-in for the real GTZAN corpus, BASELINE.json
config 5; reference pipeline veles/genre_recognition.xml:1-30).

Each genre is a parametric style over the dimensions the reference's
feature pipeline actually measures — spectral center/rolloff (tone
register + harmonic rolloff), zero crossings (noisiness), energy
envelope and beat autocorrelation (tempo + beat sharpness).  Per-track
jitter overlaps neighbouring styles so the task is learnable but not
separable by any single feature — like real genres.

Calibration note (the scenes.py discipline): real-GTZAN accuracy with
features of this family is ~61% (Tzanetakis & Cook 2002, the corpus'
source paper) with a GMM and 70-80% with MLPs in later literature.
The surrogate difficulty was tuned (jitter/noise levels below) until
the shipped MLP landed in that band rather than saturating — see
QUALITY_r04.json for the measured value.
"""

import getpass
import hashlib
import os
import tempfile

import numpy

#: style table: fundamental (Hz), harmonic count, harmonic decay,
#: tempo (BPM), beat depth, noise floor, noise lowpass (Hz or None)
GENRES = {
    "drone":     dict(f0=82,  nh=9, decay=0.92, bpm=0,   beat=0.0,
                      noise=0.04, cut=900),
    "ballad":    dict(f0=147, nh=6, decay=0.80, bpm=72,  beat=0.35,
                      noise=0.06, cut=2400),
    "folk":      dict(f0=196, nh=5, decay=0.70, bpm=96,  beat=0.45,
                      noise=0.08, cut=3600),
    "pop":       dict(f0=262, nh=4, decay=0.62, bpm=118, beat=0.65,
                      noise=0.10, cut=5200),
    "dance":     dict(f0=220, nh=3, decay=0.55, bpm=132, beat=0.85,
                      noise=0.12, cut=7000),
    "techno":    dict(f0=110, nh=2, decay=0.50, bpm=144, beat=0.95,
                      noise=0.16, cut=9000),
    "rock":      dict(f0=330, nh=6, decay=0.75, bpm=126, beat=0.70,
                      noise=0.22, cut=8000),
    "metal":     dict(f0=392, nh=8, decay=0.85, bpm=152, beat=0.75,
                      noise=0.30, cut=None),
    "ambient":   dict(f0=523, nh=3, decay=0.45, bpm=56,  beat=0.15,
                      noise=0.05, cut=1800),
    "noisewave": dict(f0=660, nh=2, decay=0.40, bpm=84,  beat=0.50,
                      noise=0.40, cut=None),
}

#: pentatonic steps the per-track melody walks over (semitone ratios)
_SCALE = (1.0, 9 / 8, 5 / 4, 3 / 2, 5 / 3, 2.0)


def synth_track(style, rng, seconds=10.0, rate=22050):
    """One track of the given style: a melodic walk of harmonic notes
    with a beat-gated amplitude envelope over coloured noise."""
    n = int(seconds * rate)
    t = numpy.arange(n) / rate
    # WIDE jitter: neighbouring styles must overlap per-track or the
    # task saturates (a first cut with ±18%/±30% probed at 97% logreg
    # accuracy — nothing like real genres; these ranges landed the
    # probe in the literature band, see the module docstring)
    jit = lambda v, frac: v * rng.uniform(1 - frac, 1 + frac)
    f0 = jit(style["f0"], 0.45)
    decay = min(0.97, jit(style["decay"], 0.30))
    bpm = jit(style["bpm"], 0.30) if style["bpm"] else 0.0
    beat_depth = min(1.0, jit(style["beat"], 0.55)) if style["beat"] \
        else 0.0
    noise_level = jit(style["noise"], 0.75)
    nh = max(1, int(round(jit(style["nh"], 0.4))))

    # melodic walk: a new scale note every ~0.5 s
    note_len = int(0.5 * rate)
    n_notes = n // note_len + 1
    steps = rng.integers(0, len(_SCALE), n_notes)
    freq = numpy.repeat(f0 * numpy.take(_SCALE, steps), note_len)[:n]
    phase = 2 * numpy.pi * numpy.cumsum(freq) / rate

    sig = numpy.zeros(n, numpy.float32)
    for h in range(1, nh + 1):
        sig += (decay ** (h - 1)) * numpy.sin(h * phase).astype(
            numpy.float32)
    sig /= max(1.0, numpy.abs(sig).max())

    if bpm:
        beat_hz = bpm / 60.0
        env = (1 - beat_depth) + beat_depth * numpy.clip(
            numpy.sin(2 * numpy.pi * beat_hz * t
                      + rng.uniform(0, 2 * numpy.pi)) * 4, 0, 1)
        sig = sig * env.astype(numpy.float32)

    noise = rng.normal(0, 1, n).astype(numpy.float32)
    cut = style["cut"]
    if cut:
        # one-pole lowpass colours the noise (shifts ZCR + rolloff)
        alpha = numpy.exp(-2 * numpy.pi * cut / rate)
        from scipy.signal import lfilter
        noise = lfilter([1 - alpha], [1, -alpha], noise).astype(
            numpy.float32)
        noise /= max(1e-6, numpy.abs(noise).max())
    sig = sig + noise_level * noise
    return (0.8 * sig / max(1e-6, numpy.abs(sig).max())).astype(
        numpy.float32)


def default_cache_dir(tracks_per_genre=40, seconds=10.0, rate=22050,
                      seed=4242):
    """Per-user, parameter-hashed cache path: a shared machine's /tmp
    can't collide across users, and changing the generator parameters
    (or the style table) invalidates the cache instead of silently
    reusing a stale tree."""
    recipe = hashlib.sha256(repr(
        (sorted(GENRES.items()), _SCALE, tracks_per_genre, seconds,
         rate, seed)).encode()).hexdigest()[:12]
    user = getpass.getuser() or "nouser"
    return os.path.join(tempfile.gettempdir(),
                        "veles_tpu_tones_%s_%s" % (user, recipe))


def generate(dest=None, tracks_per_genre=40, seconds=10.0, rate=22050,
             seed=4242):
    """Write the GTZAN-layout wav tree ``dest/<genre>/<idx>.wav``
    (default: :func:`default_cache_dir`); returns the tree path.
    Idempotent: skips generation when the tree is already complete."""
    if dest is None:
        dest = default_cache_dir(tracks_per_genre, seconds, rate, seed)
    from scipy.io import wavfile
    rng = numpy.random.default_rng(seed)
    complete = all(
        os.path.isfile(os.path.join(
            dest, g, "%05d.wav" % (tracks_per_genre - 1)))
        for g in GENRES)
    if complete:
        return dest
    for genre, style in GENRES.items():
        d = os.path.join(dest, genre)
        os.makedirs(d, exist_ok=True)
        for i in range(tracks_per_genre):
            sig = synth_track(style, rng, seconds, rate)
            wavfile.write(os.path.join(d, "%05d.wav" % i), rate,
                          (sig * 32767).astype(numpy.int16))
    return dest
