"""Procedurally generated quality-benchmark datasets.

The build environment has zero egress, so the reference's published
quality numbers (MNIST 1.48% / CIFAR-10 17.21% validation error,
docs/source/manualrst_veles_algorithms.rst:31,51) cannot be reproduced
on the real corpora here.  These generators are the documented
surrogates of matched *task structure*: 10-way image classification
where classes overlap through deformation and noise, so a model must
learn shape — not color statistics — to win.  The quality harness
(``quality.py`` at the repo root) trains the reference configs on them
and records the results in ``QUALITY_r<N>.json``; when real IDX/pickle
corpora are placed under ``root.common.dirs.datasets`` the same
workflows train on the real thing instead.
"""

from veles_tpu.datasets.glyphs import render_digits  # noqa: F401
from veles_tpu.datasets.scenes import render_scenes  # noqa: F401
