"""Procedural object-scene surrogate (CIFAR-shaped: 32x32 RGB).

Ten shape classes rendered over noisy gradient backgrounds with
randomized color, position, scale and rotation.  Colors are sampled
independently of class, so — like CIFAR — color statistics carry no
label signal: a classifier must read shape.  Difficulty comes from
scale/rotation ranges, low object/background contrast draws, pixel
noise, and a random occluding bar; the ranges are tuned so the
caffe-quick conv net (BASELINE config 2's architecture) lands in a
mid-teens validation-error band rather than saturating.

Classes: 0 disk, 1 ring, 2 triangle, 3 square, 4 cross, 5 horizontal
stripes, 6 vertical stripes, 7 checker, 8 crescent, 9 dumbbell.
"""

import numpy


def _rot(gx, gy, cx, cy, theta):
    ct, st = numpy.cos(theta), numpy.sin(theta)
    x = gx - cx[:, None, None]
    y = gy - cy[:, None, None]
    return (ct[:, None, None] * x + st[:, None, None] * y,
            -st[:, None, None] * x + ct[:, None, None] * y)


def _shape_mask(cls, gx, gy, rng, n):
    """Soft [n, H, W] mask in [0,1] for one class.

    Every class has a *morph* parameter sweeping part of its population
    toward another class's appearance (fat ring -> disk, fat cross ->
    square, huge stripe period -> blob, shallow crescent bite -> disk,
    merged dumbbell -> disk…).  That overlap is the irreducible
    ambiguity that keeps a large training set from driving the error
    to zero — the CIFAR-like part of the task."""
    cx = rng.uniform(0.35, 0.65, n)
    cy = rng.uniform(0.35, 0.65, n)
    r = rng.uniform(0.16, 0.3, n)
    # bounded rotation: under uniform 0..2pi the horizontal- and
    # vertical-stripe classes would be the SAME distribution (so would
    # rotated checkers) — +-20 degrees keeps orientation a label signal
    # while still forcing rotation tolerance
    theta = rng.uniform(-0.35, 0.35, n)
    morph = rng.uniform(0.0, 1.0, n)[:, None, None]
    x, y = _rot(gx, gy, cx, cy, theta)
    rr = r[:, None, None]
    soft = 60.0
    d = numpy.sqrt(x * x + y * y)
    if cls == 0:      # disk
        m = d - rr
    elif cls == 1:    # ring; fat rings (high morph) approach the disk
        m = numpy.abs(d - rr * (1 - 0.3 * morph)) \
            - (0.2 + 0.55 * morph) * rr
    elif cls == 2:    # triangle (3 half-planes)
        k = numpy.sqrt(3.0)
        m = numpy.maximum.reduce([y - rr * 0.5,
                                  -y - k * x - rr * 0.5,
                                  -y + k * x - rr * 0.5]) / 1.5
    elif cls == 3:    # square
        m = numpy.maximum(numpy.abs(x), numpy.abs(y)) - rr * 0.85
    elif cls == 4:    # cross; fat arms (high morph) approach the square
        w = (0.25 + 0.5 * morph) * rr
        arm = numpy.minimum(
            numpy.maximum(numpy.abs(x) - w, numpy.abs(y) - rr),
            numpy.maximum(numpy.abs(y) - w, numpy.abs(x) - rr))
        m = arm
    elif cls == 5:    # horizontal stripes; huge periods show one band
        period = (0.6 + 1.4 * morph[:, :, 0:1]) * rr
        band = numpy.abs(((y / period) % 1.0) - 0.5) - 0.22
        m = numpy.maximum(band * period * 2, d - 1.6 * rr)
    elif cls == 6:    # vertical stripes
        period = (0.6 + 1.4 * morph[:, :, 0:1]) * rr
        band = numpy.abs(((x / period) % 1.0) - 0.5) - 0.22
        m = numpy.maximum(band * period * 2, d - 1.6 * rr)
    elif cls == 7:    # checker; huge cells look like stripes/squares
        period = (0.7 + 1.3 * morph[:, :, 0:1]) * rr
        sq = (numpy.floor(x / period) + numpy.floor(y / period)) % 2
        m = numpy.where(sq > 0.5, -0.01, 0.01) + 0 * d
        m = numpy.maximum(m, d - 1.6 * rr)
    elif cls == 8:    # crescent; shallow bites approach the disk
        off = (0.25 + 0.6 * morph) * rr
        d2 = numpy.sqrt((x - off) ** 2 + y * y)
        m = numpy.maximum(d - rr, -(d2 - 0.75 * rr))
    else:             # dumbbell; fat bars merge into one blob
        da = numpy.sqrt((x - 0.8 * rr) ** 2 + y * y) - 0.55 * rr
        db = numpy.sqrt((x + 0.8 * rr) ** 2 + y * y) - 0.55 * rr
        bar = numpy.maximum(numpy.abs(y) - (0.1 + 0.45 * morph) * rr,
                            numpy.abs(x) - 0.8 * rr)
        m = numpy.minimum.reduce([da, db, bar])
    return 1.0 / (1.0 + numpy.exp(soft * m))


def render_scenes(n, seed=0, size=32, noise=0.07, contrast_min=0.4,
                  label_noise=0.115, _chunk=4096):
    """Render ``n`` scenes; returns (images [n,size,size,3] f32 in
    [0,1], labels [n] int64).

    ``label_noise`` uniformly corrupts that fraction of labels (train
    AND validation, like real annotation noise).  The class morphs
    above supply ~4% of irreducible confusion; the label noise supplies
    the rest.  Calibration, measured with the caffe-quick net at
    50k/10k (BASELINE config 2): label_noise 0 -> 3.96% val err,
    0.08 -> 12.73%, 0.10 -> 14.82%, 0.115 -> 17.79% — matching
    CIFAR-10's published 17.21% (manualrst_veles_algorithms.rst:51).
    Documented calibration, not a hidden fudge: set ``label_noise=0``
    for the clean variant."""
    if n > _chunk:
        parts = [render_scenes(min(_chunk, n - i), seed + 104729 * i,
                               size, noise, contrast_min, label_noise)
                 for i in range(0, n, _chunk)]
        return (numpy.concatenate([p[0] for p in parts]),
                numpy.concatenate([p[1] for p in parts]))
    rng = numpy.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    px = (numpy.arange(size, dtype=numpy.float32) + 0.5) / size
    gxx, gyy = numpy.meshgrid(px, px)
    gx = gxx[None]  # [1, H, W] broadcast over samples
    gy = gyy[None]

    # background: linear gradient between two random colors + noise
    c0 = rng.uniform(0.0, 1.0, (n, 1, 1, 3)).astype(numpy.float32)
    c1 = rng.uniform(0.0, 1.0, (n, 1, 1, 3)).astype(numpy.float32)
    ang = rng.uniform(0, 2 * numpy.pi, n)
    t = (numpy.cos(ang)[:, None, None] * gxx[None]
         + numpy.sin(ang)[:, None, None] * gyy[None])
    t = (t - t.min(axis=(1, 2), keepdims=True))
    t = t / numpy.maximum(t.max(axis=(1, 2), keepdims=True), 1e-6)
    img = c0 + (c1 - c0) * t[..., None]

    # object color: random, pushed away from the local background mean
    # by at least `contrast_min` so shapes are visible but can be faint
    obj = rng.uniform(0.0, 1.0, (n, 3)).astype(numpy.float32)
    bg_mean = (c0[:, 0, 0] + c1[:, 0, 0]) / 2
    delta = obj - bg_mean
    norm = numpy.linalg.norm(delta, axis=1, keepdims=True)
    scale = numpy.maximum(contrast_min / numpy.maximum(norm, 1e-6), 1.0)
    obj = numpy.clip(bg_mean + delta * scale, 0, 1)

    mask = numpy.zeros((n, size, size), numpy.float32)
    for cls in range(10):
        sel = labels == cls
        k = int(sel.sum())
        if k:
            mask[sel] = _shape_mask(cls, gx, gy, rng, k)
    img = img + mask[..., None] * (obj[:, None, None, :] - img)

    # occluding bar (random thin stripe of a third color)
    occ = rng.random(n) < 0.35
    if occ.any():
        k = int(occ.sum())
        oc = rng.uniform(0, 1, (k, 1, 1, 3)).astype(numpy.float32)
        pos = rng.uniform(0.1, 0.9, k)
        width = rng.uniform(0.04, 0.1, k)
        horiz = rng.random(k) < 0.5
        coord = numpy.where(horiz[:, None, None], gyy[None], gxx[None])
        bar = (numpy.abs(coord - pos[:, None, None])
               < width[:, None, None]).astype(numpy.float32)
        sub = img[occ]
        img[occ] = sub + bar[..., None] * (oc - sub)

    img += rng.normal(scale=noise, size=img.shape)

    if label_noise > 0:
        flip = rng.random(n) < label_noise
        labels = numpy.where(flip, rng.integers(0, 10, n), labels)
    return numpy.clip(img, 0, 1).astype(numpy.float32), labels
