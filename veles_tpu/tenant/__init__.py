"""Per-tenant request identity and admission economics.

:mod:`veles_tpu.tenant.admission` resolves a tenant id at the router
edge (hash of the bearer token, or an explicit ``X-Veles-Tenant``
from loopback), tags every request with a cardinality-bounded label,
and — when ``root.common.tenant.enabled`` — enforces per-tenant
token-bucket rate limits and a weighted-fair concurrency lane so a
flooding tenant degrades only itself.
"""

from veles_tpu.tenant.admission import TenantAdmission, resolve_tenant

__all__ = ("TenantAdmission", "resolve_tenant")
