"""Per-tenant admission economics at the router edge.

A fleet that serves more than one client needs a *tenant* notion
before any fairness story can exist: one flooding client must not be
able to starve everyone behind the shared queue.  This module gives
the router three layers, each independently cheap:

- **identity** — :func:`resolve_tenant` derives a stable tenant id
  from the request: an explicit ``X-Veles-Tenant`` header when the
  peer is loopback (the trusted-proxy / test shape), else a short
  hash of the ``Authorization: Bearer`` token (the credential IS the
  tenant; the raw secret never appears in logs or labels), else
  ``"anon"``.
- **tagging** — :meth:`TenantAdmission.tag` maps the raw id onto a
  cardinality-bounded metrics label (the first
  ``root.common.tenant.label_cardinality`` distinct tenants keep
  their own label, later arrivals share ``"other"``) and injects it
  as the forwarded ``X-Veles-Tenant`` header, so router metrics,
  trace spans and replica-side queue spans all agree on one value.
  Tagging is ALWAYS on — observability precedes enforcement.
- **enforcement** (``root.common.tenant.enabled``, default off) — a
  per-tenant token bucket (``rate`` tokens/sec, ``burst`` capacity;
  an over-rate submit is a structured 429 + ``Retry-After``) and a
  weighted-fair concurrency lane: at most ``max_concurrent``
  requests of one tenant proxy at once, later ones WAIT on their own
  tenant's asyncio semaphore (equal weights — fairness by equal
  concurrency shares) while other tenants' traffic flows untouched.

Buckets and lanes are keyed by the RAW tenant id — a flooder that
falls into the ``"other"`` label bucket still gets its own private
rate limit, so label-cardinality bounding never lets tenants share
(or exhaust) each other's budgets.  The bucket map is LRU-capped so
an id-spraying client cannot grow router memory without bound.
"""

import asyncio
import hashlib
import re
import threading
import time

from veles_tpu.logger import events
from veles_tpu.telemetry import metrics

__all__ = ("resolve_tenant", "TenantAdmission")


def _tenant_conf(name, default):
    from veles_tpu.config import root
    return root.common.tenant.get(name, default)


#: characters allowed through from an explicit X-Veles-Tenant header
#: (everything else flattens to "_" — the id becomes a label value)
_UNSAFE = re.compile(r"[^A-Za-z0-9._-]")

#: explicit tenant ids are clipped — a label value, not a payload
_MAX_ID = 32

#: token-bucket map cap: beyond this many distinct raw ids the
#: stalest bucket is evicted (an evicted flooder re-enters with a
#: FULL bucket, which only helps it once per eviction)
_MAX_BUCKETS = 1024


def resolve_tenant(headers, loopback=False):
    """The request's raw tenant id from its (lowercase-keyed)
    headers: an explicit ``X-Veles-Tenant`` when the peer is trusted
    (loopback — the router itself forwards the resolved label this
    way), else ``t-<8 hex>`` from the bearer token's SHA-256 (the
    credential identifies the tenant; the secret never leaves the
    hash), else ``"anon"``."""
    if loopback:
        explicit = headers.get("x-veles-tenant")
        if explicit:
            return _UNSAFE.sub("_", str(explicit))[:_MAX_ID]
    auth = headers.get("authorization", "")
    if auth[:7].lower() == "bearer " and auth[7:].strip():
        digest = hashlib.sha256(auth[7:].strip().encode()).hexdigest()
        return "t-%s" % digest[:8]
    return "anon"


def _throttled_series():
    return metrics.counter(
        "veles_router_tenant_throttled_total",
        "requests answered 429 at the tenant admission lane (token "
        "bucket over rate, or the tenant's concurrency lane never "
        "freed a seat), by bounded tenant label — the "
        "tenant_throttled alert rule watches its rate",
        labelnames=("tenant",))


class TenantAdmission:
    """Router-edge tenant tagging + (optionally) enforcement.

    Thread-safe for the sync surface (``tag``/``throttle``/label
    bookkeeping); :meth:`acquire`/:meth:`release` touch asyncio
    primitives and belong on the router's event loop."""

    def __init__(self):
        self._lock = threading.Lock()
        self._labels = {}     # raw id -> bounded label (stable)
        self._buckets = {}    # raw id -> [tokens, last_refill]
        self._lanes = {}      # raw id -> asyncio.Semaphore (loop only)
        self.throttled = 0
        self._global = _throttled_series()

    # -- config (read live so tests/operators can flip knobs) -----------

    @property
    def enabled(self):
        return bool(_tenant_conf("enabled", False))

    @property
    def rate(self):
        return float(_tenant_conf("rate", 0.0))

    @property
    def burst(self):
        return float(_tenant_conf("burst", 0.0))

    @property
    def max_concurrent(self):
        return int(_tenant_conf("max_concurrent", 0))

    @property
    def label_cardinality(self):
        return int(_tenant_conf("label_cardinality", 8))

    # -- identity + label -------------------------------------------------

    def label(self, tenant):
        """The bounded metrics label for a raw id: first-N distinct
        tenants keep their own (stable across the process — no top-N
        churn re-labeling a tenant mid-flight), the rest share
        ``"other"``."""
        tenant = str(tenant)
        with self._lock:
            lbl = self._labels.get(tenant)
            if lbl is None:
                lbl = tenant if len(self._labels) \
                    < self.label_cardinality else "other"
                self._labels[tenant] = lbl
            return lbl

    def tag(self, headers, loopback=False):
        """Resolve the raw tenant id and inject its bounded label as
        the forwarded ``x-veles-tenant`` header (replica spans and
        metrics then agree with the router's).  Returns the RAW id —
        the key buckets and lanes use."""
        raw = resolve_tenant(headers, loopback=loopback)
        headers["x-veles-tenant"] = self.label(raw)
        return raw

    # -- token bucket -----------------------------------------------------

    def throttle(self, tenant, now=None):
        """One admission through the tenant's token bucket: None to
        admit, else the ``Retry-After`` seconds for a structured 429
        (already counted in the throttle metric).  Disabled (or
        rate <= 0) admits everything."""
        if not self.enabled:
            return None
        rate = self.rate
        if rate <= 0:
            return None
        cap = max(1.0, self.burst or rate)
        now = time.monotonic() if now is None else now
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                if len(self._buckets) >= _MAX_BUCKETS:
                    stale = min(self._buckets,
                                key=lambda t: self._buckets[t][1])
                    del self._buckets[stale]
                bucket = self._buckets[tenant] = [cap, now]
            tokens, last = bucket
            tokens = min(cap, tokens + (now - last) * rate)
            if tokens >= 1.0:
                bucket[0] = tokens - 1.0
                bucket[1] = now
                return None
            bucket[0] = tokens
            bucket[1] = now
        self.record_throttled(tenant)
        return (1.0 - tokens) / rate

    # -- weighted-fair concurrency lane (router loop only) ----------------

    def _lane(self, tenant):
        sem = self._lanes.get(tenant)
        if sem is None:
            sem = self._lanes[tenant] = asyncio.Semaphore(
                self.max_concurrent)
        return sem

    async def acquire(self, tenant, timeout):
        """Take one of the tenant's concurrency seats, waiting (in
        the tenant's OWN queue — other tenants never wait here) up to
        ``timeout``.  Returns ``"seat"`` when a seat was taken
        (:meth:`release` is then owed), ``"free"`` when the lane is
        not enforcing, or None (counted as throttled) when the lane
        stayed full."""
        if not self.enabled or self.max_concurrent <= 0:
            return "free"
        try:
            await asyncio.wait_for(self._lane(tenant).acquire(),
                                   timeout)
            return "seat"
        except asyncio.TimeoutError:
            self.record_throttled(tenant)
            return None

    def release(self, tenant):
        sem = self._lanes.get(tenant)
        if sem is not None:
            sem.release()

    # -- accounting -------------------------------------------------------

    def record_throttled(self, tenant):
        lbl = self.label(tenant)
        with self._lock:
            self.throttled += 1
        self._global.labels(tenant=lbl).inc()
        events.record("tenant.throttled", "single",
                      cls="TenantAdmission", tenant=lbl)

    def snapshot(self):
        with self._lock:
            return {"enabled": self.enabled,
                    "tenants_seen": len(self._labels),
                    "throttled": self.throttled}
