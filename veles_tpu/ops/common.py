"""Shared helpers for the native-kernel modules.

Single source of truth for the accelerator-platform whitelist that
``ops.flash``, ``ops.pallas_attention`` and ``ops.lrn`` all gate on —
three independent copies drifted in round 4 (ADVICE.md r4 #1)."""

import jax

#: platforms whose devices run real Mosaic kernels ("axon" is the
#: tunneled TPU platform the driver exposes)
ACCEL_PLATFORMS = ("tpu", "axon")


def resolve_backend(backend=None):
    """The platform a computation targets: the caller's device platform
    when known (units pass ``unit.device.jax_device.platform``), else
    the process default backend as a last resort."""
    return backend if backend is not None else jax.default_backend()


def use_interpret(backend=None):
    """True when pallas kernels must run under ``interpret=True`` —
    i.e. the target device is not a TPU.  Keying off the *target*
    platform (not the process default) matters both ways: a
    CPU-targeted program in a TPU-default process must not trace a
    Mosaic kernel, and a TPU-targeted program in a CPU-default process
    must not silently run interpret-mode kernels on the chip."""
    return resolve_backend(backend) not in ACCEL_PLATFORMS
