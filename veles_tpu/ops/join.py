"""InputJoiner — concatenate N inputs along the feature axis.

Rebuild of veles/input_joiner.py:49-212 and its Jinja-templated copy
kernel (ocl/join.jcl, cuda/join.jcu).  On TPU this is ``jnp.concatenate``
inside the fused segment; XLA lays out the copies.
"""

import jax.numpy as jnp
import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.units import MissingDemand


class InputJoiner(AcceleratedUnit):
    """Joins ``inputs`` (list of Arrays) into ``output`` along axis 1,
    flattening trailing dims (ref: veles/input_joiner.py:49)."""

    WRITES = ("output",)

    def __init__(self, workflow, inputs=None, **kwargs):
        super(InputJoiner, self).__init__(workflow, **kwargs)
        self.inputs = list(inputs) if inputs else []
        self.output = Array()
        for i, arr in enumerate(self.inputs):
            setattr(self, "input_%d" % i, arr)

    @property
    def reads(self):
        return tuple("input_%d" % i for i in range(len(self.inputs)))

    def link_inputs(self, other, *attrs):
        """Append ``other``'s attrs to the join list
        (ref: input_joiner.py link protocol)."""
        for a in attrs:
            arr = getattr(other, a)
            setattr(self, "input_%d" % len(self.inputs), arr)
            self.inputs.append(arr)
        return self

    def initialize(self, device=None, **kwargs):
        if not self.inputs or not all(bool(a) for a in self.inputs):
            raise MissingDemand(self, {"inputs"})
        batch = self.inputs[0].shape[0]
        width = sum(int(numpy.prod(a.shape[1:])) for a in self.inputs)
        self.output.reset(numpy.zeros((batch, width),
                                      self.inputs[0].dtype))
        super(InputJoiner, self).initialize(device=device, **kwargs)

    def step(self, **tensors):
        flat = [tensors["input_%d" % i].reshape(tensors["input_%d" % i]
                                                .shape[0], -1)
                for i in range(len(self.inputs))]
        return {"output": jnp.concatenate(flat, axis=1)}
