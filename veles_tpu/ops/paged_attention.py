"""Block-table (paged) decode attention — the serving-side attention
core over a PagedAttention-style KV layout (Kwon et al., SOSP 2023).

K/V live in per-layer POOLS of fixed-size blocks
(``[num_blocks, block_size, d]``); a request owns a *block table* — the
ordered list of physical block ids holding its sequence — instead of a
dense ``[window, d]`` row.  The decode step then

- **scatters** the new token's K/V into ``table[pos // bs]`` at row
  ``pos % bs`` (each live block belongs to exactly ONE slot, so the
  scatter never races another request), and
- **gathers** only the table's blocks — ``[B, T·bs, d]`` where ``T``
  is the caller's *block bucket* (power-of-two over the deepest active
  slot), not the full window — before the usual masked softmax.

Table entries past a slot's live blocks point at physical block 0 (the
reserved TRASH block — never allocated to a request), so the gather
reads garbage that the causal mask (`key ≤ pos`) zeroes exactly:
``softmax`` turns the ``-inf`` scores into probability 0.0, and
``0.0 · v`` contributes nothing for any finite v (pools start zeroed
and only ever receive finite projections).  Padding rows of an
occupancy bucket follow the same convention: an all-zero table writes
into and reads from the trash block.

The math is row-for-row the dense per-slot step
(``TransformerBlock.apply_step_slots``) restricted to the gathered
key range — same projection dtypes, 1/sqrt(hd) scale and softmax
conventions — so greedy token streams are identical to the dense slot
cache (tested in tests/test_serving.py).  The width-K cousin
:func:`paged_verify_attention` scores a run of K1 consecutive tokens
per row in one pass — the speculative-decoding verify step
(tests/test_spec.py proves spec-on/spec-off token parity).  This jnp formulation lowers
to a gather + batched GEMM on every backend; the fused pallas kernel
(``ops/pallas_paged.py`` — gathered blocks stay in VMEM, dequant
fused for int8 pools) slots in behind the same signatures on
accelerator targets, the way ``ops/flash.py`` fronts the training
attention.  The ``*_q8`` variants below serve INT8 pools (per-row
scales beside the blocks; see serving/kv_slots.PagedKVCache), and
``paged_verify_attention_fused`` is the single-pass verify that
keeps the run's K/V out of the pool round-trip.

Tensor-parallel serving (serving/tp.py) runs these same functions
SPMD with the pools sharded HEAD-WISE over the ``tp`` mesh axis
(``[num_blocks, block_size, d/tp]`` per chip): the scatter, block
gather, per-head attention and the int8 per-row amax all partition
over the feature axis without code changes here — GSPMD keeps each
head's Q·K/probs·V chip-local (tp divides heads, so the
``[..., h, hd]`` reshape lands on whole heads), and only the output
projection downstream reduces across chips.  The int8 scales stay
replicated: their amax over the sharded axis reduces exactly, so
the quantized pool bytes are bit-identical to an unsharded pool's.
"""

import jax
import jax.numpy as jnp

#: symmetric int8 quantization range — the KV pools store
#: round(x / scale) with scale = rowmax(|x|) / 127, one f32 scale per
#: (block, row) living beside the pools, so every token row
#: round-trips within amax/254 per element and the trash block's
#: all-zero rows dequantize to exactly 0.0 (the masked-garbage-is-
#: finite invariant the fp32 path already relies on)
INT8_QMAX = 127.0


def quantize_kv_rows(x):
    """Per-row symmetric int8 quantization of K/V rows ``x``
    [..., d]: returns ``(q, scale)`` with ``q`` int8 [..., d] and
    ``scale`` f32 [...] such that ``q * scale ~= x`` (absmax scaling;
    an all-zero row gets scale 0 and dequantizes to exact zeros)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = amax / INT8_QMAX
    q = jnp.where(scale[..., None] > 0.0,
                  xf / jnp.maximum(scale[..., None], 1e-30), 0.0)
    q = jnp.clip(jnp.round(q), -INT8_QMAX, INT8_QMAX)
    return q.astype(jnp.int8), scale


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv_rows`: ``q`` int8 [..., d],
    ``scale`` [...] → [..., d] in ``dtype``."""
    return (q.astype(jnp.float32)
            * scale[..., None].astype(jnp.float32)).astype(dtype)


def paged_verify_attention(q, k_new, v_new, pool_k, pool_v, tables,
                           pos, lens, heads):
    """Score a WIDTH-K token run per row against a paged KV pool —
    the speculative-decoding verify kernel (one model pass scores a
    request's pending token plus its k drafted tokens).

    ``q``/``k_new``/``v_new`` [B, K1, d] — projections of the run,
    row n's position j sitting at sequence index ``pos[n] + j``;
    ``lens`` [B] ints (traced) — how many of the K1 positions are
    REAL for each row (1 = plain decode, k_eff + 1 for a row with
    k_eff drafts).  K/V of positions past ``lens[n]`` scatter into
    the reserved trash block (id 0) instead of the table, so bucket
    padding never corrupts a live block; their output rows are
    garbage the caller must not read.

    Position-for-position the same math as
    :func:`paged_decode_attention` (which is the K1 = 1, lens = 1
    special case): scatter first, then gather the table's blocks,
    causal mask ``key ≤ pos[n] + j`` per query.  Because the scatter
    lands before the gather, a query at position p sees the drafts
    at positions ≤ p written THIS pass — exactly the cache state a
    sequential per-token decode of those tokens would have produced.

    Returns ``(pool_k', pool_v', context)`` with context [B, K1, d]."""
    from veles_tpu import dtypes
    cd = dtypes.compute_dtype()
    b, k1, d = q.shape
    h = heads
    hd = d // h
    bs = pool_k.shape[1]
    qpos = pos[:, None] + jnp.arange(k1)[None, :]          # [B, K1]
    valid = jnp.arange(k1)[None, :] < lens[:, None]        # [B, K1]
    blk = jnp.take_along_axis(tables, qpos // bs, axis=1)
    blk = jnp.where(valid, blk, 0)                         # pad -> trash
    off = jnp.where(valid, qpos % bs, 0)
    pk = pool_k.at[blk, off].set(k_new.astype(pool_k.dtype))
    pv = pool_v.at[blk, off].set(v_new.astype(pool_v.dtype))
    kg = pk[tables]
    vg = pv[tables]
    length = kg.shape[1] * bs
    qh = q.reshape(b, k1, h, hd)
    kh = kg.astype(cd).reshape(b, length, h, hd)
    vh = vg.astype(cd).reshape(b, length, h, hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) \
        * (1.0 / jnp.sqrt(hd))
    mask = (jnp.arange(length)[None, None, :]
            <= qpos[:, :, None])[:, None]
    logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return pk, pv, jnp.einsum("bhqk,bkhd->bqhd", probs,
                              vh).reshape(b, k1, d)


def paged_decode_attention(q, k_new, v_new, pool_k, pool_v, tables,
                           pos, heads):
    """One decode position per row against a paged KV pool.

    ``q``/``k_new``/``v_new`` [B, 1, d] — the new token's projections
    (row n at ITS OWN sequence index ``pos[n]``); ``pool_k``/``pool_v``
    [num_blocks, block_size, d]; ``tables`` [B, T] physical block ids
    in sequence order (T·block_size must cover ``max(pos) + 1``);
    ``pos`` [B] ints, traced.

    Returns ``(pool_k', pool_v', context)`` — the pools with the new
    K/V scattered in, and the attention context [B, 1, d] (same dtype
    conventions as the dense slot step)."""
    from veles_tpu import dtypes
    cd = dtypes.compute_dtype()
    b, _, d = q.shape
    h = heads
    hd = d // h
    bs = pool_k.shape[1]
    blk = jnp.take_along_axis(tables, (pos // bs)[:, None], axis=1)[:, 0]
    off = pos % bs
    pk = pool_k.at[blk, off].set(k_new[:, 0].astype(pool_k.dtype))
    pv = pool_v.at[blk, off].set(v_new[:, 0].astype(pool_v.dtype))
    # gather ONLY the table's blocks — [B, T, bs, d] -> [B, T·bs, d];
    # the window never materializes
    kg = pk[tables]
    vg = pv[tables]
    length = kg.shape[1] * bs
    qh = q.reshape(b, 1, h, hd)
    kh = kg.astype(cd).reshape(b, length, h, hd)
    vh = vg.astype(cd).reshape(b, length, h, hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) \
        * (1.0 / jnp.sqrt(hd))
    mask = (jnp.arange(length)[None, :]
            <= pos[:, None])[:, None, None, :]
    logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return pk, pv, jnp.einsum("bhqk,bkhd->bqhd", probs,
                              vh).reshape(b, 1, d)


# -- int8 quantized pools ---------------------------------------------------
#
# Same math as the fp32 paths above with TWO twists: the new token's
# K/V rows quantize ON the scatter (per-row absmax scale stored at the
# same [block, row] coordinates, so scales follow blocks through every
# donate/evict/gather move by construction), and the gather
# dequantizes into the compute dtype before the usual masked softmax
# (fp32 accumulation unchanged).  On an accelerator target the gather
# + dequant + attend runs as the fused pallas kernel
# (ops/pallas_paged.py) instead of materializing the [B, T·bs, d]
# dequantized gather.

def _q8_ctx(q, pk, pv, sk, sv, tables, qpos, heads, backend):
    """Shared gather→dequant→attend tail of the q8 decode/verify
    paths: queries [B, K1, d] at positions ``qpos`` [B, K1], causal
    mask ``key <= qpos`` per query."""
    from veles_tpu import dtypes
    from veles_tpu.ops.common import use_interpret
    if not use_interpret(backend):
        from veles_tpu.ops.pallas_paged import pallas_paged_attend
        return pallas_paged_attend(q, pk, pv, tables, qpos, heads,
                                   scale_k=sk, scale_v=sv,
                                   backend=backend)
    cd = dtypes.compute_dtype()
    b, k1, d = q.shape
    h = heads
    hd = d // h
    bs = pk.shape[1]
    kg = dequantize_kv(pk[tables], sk[tables], cd)
    vg = dequantize_kv(pv[tables], sv[tables], cd)
    length = kg.shape[1] * bs
    qh = q.reshape(b, k1, h, hd)
    kh = kg.reshape(b, length, h, hd)
    vh = vg.reshape(b, length, h, hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) \
        * (1.0 / jnp.sqrt(hd))
    mask = (jnp.arange(length)[None, None, :]
            <= qpos[:, :, None])[:, None]
    logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, vh).reshape(b, k1, d)


def paged_decode_attention_q8(q, k_new, v_new, pool_k, pool_v,
                              scale_k, scale_v, tables, pos, heads,
                              backend=None):
    """:func:`paged_decode_attention` over INT8 pools: the new
    token's K/V quantize on the scatter (scale written beside them at
    ``scale[blk, off]``), the gather dequantizes block rows with
    their scales, attention accumulates in f32.  ``scale_k`` /
    ``scale_v`` [num_blocks, block_size] f32 ride beside the pools.

    Returns ``(pool_k', pool_v', scale_k', scale_v', context)``."""
    bs = pool_k.shape[1]
    blk = jnp.take_along_axis(tables, (pos // bs)[:, None],
                              axis=1)[:, 0]
    off = pos % bs
    qk, sk_new = quantize_kv_rows(k_new[:, 0])
    qv, sv_new = quantize_kv_rows(v_new[:, 0])
    pk = pool_k.at[blk, off].set(qk)
    pv = pool_v.at[blk, off].set(qv)
    sk = scale_k.at[blk, off].set(sk_new)
    sv = scale_v.at[blk, off].set(sv_new)
    ctx = _q8_ctx(q, pk, pv, sk, sv, tables, pos[:, None], heads,
                  backend)
    return pk, pv, sk, sv, ctx


def paged_verify_attention_q8(q, k_new, v_new, pool_k, pool_v,
                              scale_k, scale_v, tables, pos, lens,
                              heads, backend=None):
    """:func:`paged_verify_attention` over INT8 pools — the fused
    speculative-verify path: ONE quantizing scatter of the width-K1
    run (padding past ``lens`` lands in the trash block, scale
    included), then ONE gather→dequant→attend pass (the pallas kernel
    on accelerator targets).  In-pass keys read back QUANTIZED —
    verify sees exactly the cache state later decode steps will read,
    which is what the quality gate measures.

    Returns ``(pool_k', pool_v', scale_k', scale_v', context)``."""
    b, k1, d = q.shape
    bs = pool_k.shape[1]
    qpos = pos[:, None] + jnp.arange(k1)[None, :]          # [B, K1]
    valid = jnp.arange(k1)[None, :] < lens[:, None]        # [B, K1]
    blk = jnp.take_along_axis(tables, qpos // bs, axis=1)
    blk = jnp.where(valid, blk, 0)                         # pad -> trash
    off = jnp.where(valid, qpos % bs, 0)
    qk, sk_new = quantize_kv_rows(k_new)
    qv, sv_new = quantize_kv_rows(v_new)
    pk = pool_k.at[blk, off].set(qk)
    pv = pool_v.at[blk, off].set(qv)
    sk = scale_k.at[blk, off].set(sk_new)
    sv = scale_v.at[blk, off].set(sv_new)
    ctx = _q8_ctx(q, pk, pv, sk, sv, tables, qpos, heads, backend)
    return pk, pv, sk, sv, ctx


def paged_verify_attention_fused(q, k_new, v_new, pool_k, pool_v,
                                 tables, pos, lens, heads,
                                 backend=None):
    """Single-pass fp32 verify.  The PR 9 two-pass path scatters the
    run's K/V into the POOL and then gathers it back out before
    attending — the attention waits on a write to (and under jit
    without donation, a full copy of) the multi-megabyte pool just to
    read back the handful of rows it wrote.  Here the gather reads
    the PRE-scatter pool and the run's rows are scattered into the
    small GATHERED buffer instead ([B, T·bs, d] — the write is
    O(batch·k), not O(pool)), which takes the pool update off the
    attention's critical path entirely: the engine donates the pool
    buffers to this step, so the scatter lands in place and the
    per-step pool copy disappears.

    The gathered buffer ends up elementwise IDENTICAL to the
    two-pass gather at every causally-visible position, and the
    attention subgraph has the same shapes and ops — valid output
    rows are bit-identical to :func:`paged_verify_attention`
    (rows past ``lens`` are garbage under both, as documented).

    On an accelerator target the gather+attend half runs as the
    fused pallas kernel instead (ops/pallas_paged.py), which also
    never materializes the gather.

    Returns ``(pool_k', pool_v', context)`` like the two-pass path."""
    from veles_tpu import dtypes
    from veles_tpu.ops.common import use_interpret
    cd = dtypes.compute_dtype()
    b, k1, d = q.shape
    h = heads
    hd = d // h
    bs = pool_k.shape[1]
    qpos = pos[:, None] + jnp.arange(k1)[None, :]          # [B, K1]
    valid = jnp.arange(k1)[None, :] < lens[:, None]        # [B, K1]
    blk = jnp.take_along_axis(tables, qpos // bs, axis=1)
    blk = jnp.where(valid, blk, 0)                         # pad -> trash
    off = jnp.where(valid, qpos % bs, 0)
    pk = pool_k.at[blk, off].set(k_new.astype(pool_k.dtype))
    pv = pool_v.at[blk, off].set(v_new.astype(pool_v.dtype))
    if not use_interpret(backend):
        # accelerator target: the fused pallas kernel attends over
        # the POST-scatter pool (same numerics as the two-pass jnp
        # path, without materializing the gather)
        from veles_tpu.ops.pallas_paged import pallas_paged_attend
        return pk, pv, pallas_paged_attend(q, pk, pv, tables, qpos,
                                           heads, backend=backend)
    kg = pool_k[tables].astype(cd)                # pre-scatter pools
    vg = pool_v[tables].astype(cd)
    length = kg.shape[1] * bs
    kg = kg.reshape(b, length, d)
    vg = vg.reshape(b, length, d)
    # the run's rows land in the GATHERED buffer — the same values
    # the two-pass gather reads back at these positions (per-row
    # qpos entries are distinct; positions past a row's len only
    # ever feed masked scores)
    rows = jnp.arange(b)[:, None]
    kg = kg.at[rows, qpos].set(k_new.astype(cd))
    vg = vg.at[rows, qpos].set(v_new.astype(cd))
    qh = q.reshape(b, k1, h, hd)
    kh = kg.reshape(b, length, h, hd)
    vh = vg.reshape(b, length, h, hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) \
        * (1.0 / jnp.sqrt(hd))
    mask = (jnp.arange(length)[None, None, :]
            <= qpos[:, :, None])[:, None]
    logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return pk, pv, jnp.einsum("bhqk,bkhd->bqhd", probs,
                              vh).reshape(b, k1, d)
