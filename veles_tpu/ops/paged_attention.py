"""Block-table (paged) decode attention — the serving-side attention
core over a PagedAttention-style KV layout (Kwon et al., SOSP 2023).

K/V live in per-layer POOLS of fixed-size blocks
(``[num_blocks, block_size, d]``); a request owns a *block table* — the
ordered list of physical block ids holding its sequence — instead of a
dense ``[window, d]`` row.  The decode step then

- **scatters** the new token's K/V into ``table[pos // bs]`` at row
  ``pos % bs`` (each live block belongs to exactly ONE slot, so the
  scatter never races another request), and
- **gathers** only the table's blocks — ``[B, T·bs, d]`` where ``T``
  is the caller's *block bucket* (power-of-two over the deepest active
  slot), not the full window — before the usual masked softmax.

Table entries past a slot's live blocks point at physical block 0 (the
reserved TRASH block — never allocated to a request), so the gather
reads garbage that the causal mask (`key ≤ pos`) zeroes exactly:
``softmax`` turns the ``-inf`` scores into probability 0.0, and
``0.0 · v`` contributes nothing for any finite v (pools start zeroed
and only ever receive finite projections).  Padding rows of an
occupancy bucket follow the same convention: an all-zero table writes
into and reads from the trash block.

The math is row-for-row the dense per-slot step
(``TransformerBlock.apply_step_slots``) restricted to the gathered
key range — same projection dtypes, 1/sqrt(hd) scale and softmax
conventions — so greedy token streams are identical to the dense slot
cache (tested in tests/test_serving.py).  The width-K cousin
:func:`paged_verify_attention` scores a run of K1 consecutive tokens
per row in one pass — the speculative-decoding verify step
(tests/test_spec.py proves spec-on/spec-off token parity).  This jnp formulation lowers
to a gather + batched GEMM on every backend; a fused pallas kernel
(keeping the gathered blocks in VMEM) would slot in behind the same
signature, the way ``ops/flash.py`` fronts the training attention.
"""

import jax
import jax.numpy as jnp


def paged_verify_attention(q, k_new, v_new, pool_k, pool_v, tables,
                           pos, lens, heads):
    """Score a WIDTH-K token run per row against a paged KV pool —
    the speculative-decoding verify kernel (one model pass scores a
    request's pending token plus its k drafted tokens).

    ``q``/``k_new``/``v_new`` [B, K1, d] — projections of the run,
    row n's position j sitting at sequence index ``pos[n] + j``;
    ``lens`` [B] ints (traced) — how many of the K1 positions are
    REAL for each row (1 = plain decode, k_eff + 1 for a row with
    k_eff drafts).  K/V of positions past ``lens[n]`` scatter into
    the reserved trash block (id 0) instead of the table, so bucket
    padding never corrupts a live block; their output rows are
    garbage the caller must not read.

    Position-for-position the same math as
    :func:`paged_decode_attention` (which is the K1 = 1, lens = 1
    special case): scatter first, then gather the table's blocks,
    causal mask ``key ≤ pos[n] + j`` per query.  Because the scatter
    lands before the gather, a query at position p sees the drafts
    at positions ≤ p written THIS pass — exactly the cache state a
    sequential per-token decode of those tokens would have produced.

    Returns ``(pool_k', pool_v', context)`` with context [B, K1, d]."""
    from veles_tpu import dtypes
    cd = dtypes.compute_dtype()
    b, k1, d = q.shape
    h = heads
    hd = d // h
    bs = pool_k.shape[1]
    qpos = pos[:, None] + jnp.arange(k1)[None, :]          # [B, K1]
    valid = jnp.arange(k1)[None, :] < lens[:, None]        # [B, K1]
    blk = jnp.take_along_axis(tables, qpos // bs, axis=1)
    blk = jnp.where(valid, blk, 0)                         # pad -> trash
    off = jnp.where(valid, qpos % bs, 0)
    pk = pool_k.at[blk, off].set(k_new.astype(pool_k.dtype))
    pv = pool_v.at[blk, off].set(v_new.astype(pool_v.dtype))
    kg = pk[tables]
    vg = pv[tables]
    length = kg.shape[1] * bs
    qh = q.reshape(b, k1, h, hd)
    kh = kg.astype(cd).reshape(b, length, h, hd)
    vh = vg.astype(cd).reshape(b, length, h, hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) \
        * (1.0 / jnp.sqrt(hd))
    mask = (jnp.arange(length)[None, None, :]
            <= qpos[:, :, None])[:, None]
    logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return pk, pv, jnp.einsum("bhqk,bkhd->bqhd", probs,
                              vh).reshape(b, k1, d)


def paged_decode_attention(q, k_new, v_new, pool_k, pool_v, tables,
                           pos, heads):
    """One decode position per row against a paged KV pool.

    ``q``/``k_new``/``v_new`` [B, 1, d] — the new token's projections
    (row n at ITS OWN sequence index ``pos[n]``); ``pool_k``/``pool_v``
    [num_blocks, block_size, d]; ``tables`` [B, T] physical block ids
    in sequence order (T·block_size must cover ``max(pos) + 1``);
    ``pos`` [B] ints, traced.

    Returns ``(pool_k', pool_v', context)`` — the pools with the new
    K/V scattered in, and the attention context [B, 1, d] (same dtype
    conventions as the dense slot step)."""
    from veles_tpu import dtypes
    cd = dtypes.compute_dtype()
    b, _, d = q.shape
    h = heads
    hd = d // h
    bs = pool_k.shape[1]
    blk = jnp.take_along_axis(tables, (pos // bs)[:, None], axis=1)[:, 0]
    off = pos % bs
    pk = pool_k.at[blk, off].set(k_new[:, 0].astype(pool_k.dtype))
    pv = pool_v.at[blk, off].set(v_new[:, 0].astype(pool_v.dtype))
    # gather ONLY the table's blocks — [B, T, bs, d] -> [B, T·bs, d];
    # the window never materializes
    kg = pk[tables]
    vg = pv[tables]
    length = kg.shape[1] * bs
    qh = q.reshape(b, 1, h, hd)
    kh = kg.astype(cd).reshape(b, length, h, hd)
    vh = vg.astype(cd).reshape(b, length, h, hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) \
        * (1.0 / jnp.sqrt(hd))
    mask = (jnp.arange(length)[None, :]
            <= pos[:, None])[:, None, None, :]
    logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return pk, pv, jnp.einsum("bhqk,bkhd->bqhd", probs,
                              vh).reshape(b, 1, d)
