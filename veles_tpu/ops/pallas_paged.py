"""Dequant-fused paged-attention pallas kernel — the block-gather
attention loop of ``ops/paged_attention.py`` as ONE kernel, for fp32
AND int8 pools.

The jnp reference path materializes the gathered table blocks as a
``[B, T·bs, d]`` tensor in HBM (dequantized to the compute dtype when
the pool is int8) before the masked softmax — for a bandwidth-bound
decode step that round-trip IS the cost.  Here each grid step DMAs
one physical block straight into VMEM (the block table rides scalar
prefetch, so the index map itself does the gather), dequantizes it
in-register against its per-row scales, and folds it into a running
online-softmax accumulation — the FlashAttention-2 decomposition of
``ops/pallas_attention.py`` restricted to one query run per row.  The
dequantized gather never exists in HBM, which is what makes int8
pools pay int8 bandwidth instead of "int8 storage, f32 traffic".

One kernel serves both step families: plain decode is the K1 = 1
special case of the width-K1 speculative verify (exactly the
relationship of the jnp pair).  The caller scatters the run's new
K/V (quantizing when int8) BEFORE invoking — the kernel then reads
the post-scatter pool, so its numerics match the two-pass jnp path
block-for-block (parity is allclose: the online softmax reorders the
reduction).

Runs under ``interpret=True`` off-TPU (``ops.common.use_interpret``,
the flash/lrn convention) — tier-1 proves parity on CPU; the Mosaic
lowering targets real chips.

Layouts: q/qpos per batch row, pools block-major
([num_blocks, block_size, d] with the per-row scales
[num_blocks, block_size] beside them); heads are folded as d = h·hd
and unfolded per-head inside the kernel (2-D dots only).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.common import use_interpret as _use_interpret

#: finite stand-in for -inf (ops/pallas_attention.py convention)
_NEG_INF = -1e30
#: lane width — running row-stats scratch replicates across it
_LANES = 128


def _attend_kernel(tables_ref, q_ref, qp_ref, k_ref, v_ref, *rest,
                   heads, head_dim, block_size, k1, quant, scale):
    """One (b, t) grid step: fold physical block ``tables[b, t]``
    into row b's online-softmax state.  ``rest`` is
    ``[sk_ref, sv_ref,] o_ref, acc_ref, m_ref, l_ref``."""
    if quant:
        sk_ref, sv_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
        sk_ref = sv_ref = None
    t = pl.program_id(1)
    nt = pl.num_programs(1)
    h, hd, bs = heads, head_dim, block_size

    @pl.when(t == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    k = k_ref[0].astype(jnp.float32)              # [bs, d]
    v = v_ref[0].astype(jnp.float32)
    if quant:                                     # dequant in VMEM
        k = k * sk_ref[0][:, None]
        v = v * sv_ref[0][:, None]
    qp = qp_ref[0]                                # [k1] positions
    cols = t * bs + jax.lax.broadcasted_iota(
        jnp.int32, (k1, bs), 1)
    keep = cols <= qp[:, None]                    # causal + trash tail
    for head in range(h):
        lo = head * hd
        qh = q_ref[0][:, lo:lo + hd].astype(jnp.float32)  # [k1, hd]
        s = jax.lax.dot_general(
            qh, k[:, lo:lo + hd], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [k1, bs]
        s = jnp.where(keep, s, _NEG_INF)
        r = head * k1
        m_prev = m_ref[r:r + k1, 0]
        m_cur = jnp.maximum(m_prev, s.max(axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_ref[r:r + k1, 0] * alpha + p.sum(axis=1)
        m_ref[r:r + k1] = jnp.broadcast_to(m_cur[:, None],
                                           (k1, _LANES))
        l_ref[r:r + k1] = jnp.broadcast_to(l_cur[:, None],
                                           (k1, _LANES))
        acc_ref[:, lo:lo + hd] = \
            acc_ref[:, lo:lo + hd] * alpha[:, None] + jax.lax.dot(
                p, v[:, lo:lo + hd],
                preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _finish():
        for head in range(h):
            lo = head * hd
            l = jnp.maximum(l_ref[head * k1:(head + 1) * k1, 0],
                            1e-30)
            o_ref[0, :, lo:lo + hd] = \
                (acc_ref[:, lo:lo + hd] / l[:, None]).astype(
                    o_ref.dtype)


def pallas_paged_attend(q, pool_k, pool_v, tables, qpos, heads,
                        scale_k=None, scale_v=None, interpret=None,
                        backend=None):
    """Block-gather attention over a (possibly int8) paged KV pool.

    ``q`` [B, K1, d] — row n's queries at sequence positions
    ``qpos`` [B, K1]; ``pool_k``/``pool_v`` [num_blocks, bs, d]
    POST-scatter (the caller wrote the run's K/V first);
    ``scale_k``/``scale_v`` [num_blocks, bs] f32 per-row dequant
    scales (None = fp32 pool); ``tables`` [B, T] physical block ids.
    Returns the attention context [B, K1, d] (f32) — same masked
    softmax as the jnp reference, accumulated online so the gathered
    blocks never materialize."""
    b, k1, d = q.shape
    bs = pool_k.shape[1]
    nt = tables.shape[1]
    hd = d // heads
    quant = scale_k is not None
    if interpret is None:
        interpret = _use_interpret(backend)
    kernel = functools.partial(
        _attend_kernel, heads=heads, head_dim=hd, block_size=bs,
        k1=k1, quant=quant, scale=1.0 / (hd ** 0.5))

    def blk_map(bi, t, tbl):
        return (tbl[bi, t], 0, 0)

    def scl_map(bi, t, tbl):
        return (tbl[bi, t], 0)

    in_specs = [
        pl.BlockSpec((1, k1, d), lambda bi, t, tbl: (bi, 0, 0)),
        pl.BlockSpec((1, k1), lambda bi, t, tbl: (bi, 0)),
        pl.BlockSpec((1, bs, d), blk_map),
        pl.BlockSpec((1, bs, d), blk_map),
    ]
    ops = [q, jnp.asarray(qpos, jnp.int32), pool_k, pool_v]
    if quant:
        in_specs += [pl.BlockSpec((1, bs), scl_map),
                     pl.BlockSpec((1, bs), scl_map)]
        ops += [scale_k, scale_v]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, nt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, k1, d),
                               lambda bi, t, tbl: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((k1, d), jnp.float32),
            pltpu.VMEM((heads * k1, _LANES), jnp.float32),
            pltpu.VMEM((heads * k1, _LANES), jnp.float32),
        ])
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, k1, d), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(tables, jnp.int32), *ops)
