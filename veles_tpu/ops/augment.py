"""In-graph data augmentation — random transforms traced INTO the
fused train step (TPU-first: the reference augmented per-minibatch on
the host with PIL, veles/loader/image.py — that would stall the span
pipeline here, so augmentation runs on device, keyed by the trainer's
per-minibatch prng, costing microseconds instead of a host hop).

The trainer applies the configured augment only on TRAIN minibatches
(models/gd.py); evaluation always sees clean data.
"""

import jax
import jax.numpy as jnp


def image_augment(flip=True, pad=0, cutout=0, shape=None):
    """The classic small-image recipe: random horizontal flip +
    random crop after reflect-padding ``pad`` pixels + optional
    ``cutout``-sized random erase.  Returns ``fn(x, key)`` for
    [batch, h, w, c] inputs — or for FLAT [batch, features]
    minibatches when ``shape=(h, w, c)`` is given (MLP pipelines like
    the MNIST sample keep their data flat; the augment reshapes in
    and out around the spatial ops)."""

    def fn(x, key):
        flat_in = shape is not None and x.ndim == 2
        if flat_in:
            x = x.reshape((x.shape[0],) + tuple(shape))
        b, h, w, c = x.shape
        kf, kc, ku = jax.random.split(key, 3)
        if flip:
            do = jax.random.bernoulli(kf, 0.5, (b,))
            x = jnp.where(do[:, None, None, None], x[:, :, ::-1, :], x)
        if pad:
            xp = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                         mode="reflect")
            off = jax.random.randint(kc, (b, 2), 0, 2 * pad + 1)

            def crop(img, o):
                return jax.lax.dynamic_slice(
                    img, (o[0], o[1], 0), (h, w, c))

            x = jax.vmap(crop)(xp, off)
        if cutout:
            # an exactly cutout×cutout box (top-left anchored so the
            # erased area matches the configured size; the box may
            # hang off the edge, like the original cutout paper)
            cy = jax.random.randint(ku, (b,), -cutout // 2, h)
            cx = jax.random.randint(jax.random.fold_in(ku, 1),
                                    (b,), -cutout // 2, w)
            yy = jnp.arange(h)[None, :, None]
            xx = jnp.arange(w)[None, None, :]
            mask = ((yy >= cy[:, None, None])
                    & (yy < cy[:, None, None] + cutout)
                    & (xx >= cx[:, None, None])
                    & (xx < cx[:, None, None] + cutout))
            x = jnp.where(mask[..., None], 0.0, x)
        if flat_in:
            x = x.reshape(b, h * w * c)
        return x

    return fn


def make_augment(kind, **kwargs):
    """Config-friendly factory: ``kind`` names the recipe."""
    if kind in ("image", "flip_crop"):
        return image_augment(**kwargs)
    raise ValueError("unknown augment kind %r" % (kind,))
