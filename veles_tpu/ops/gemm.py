"""GEMM — MXU matmul under the precision policy, plus a Pallas tiled
kernel with a fused-epilogue hook.

Rebuild of ocl/matrix_multiplication*.cl (351 LoC of hand-tiled
shared-memory GEMM in 3 precision levels) and the ``STORE_OUTPUT``
epilogue-injection hook (ref: ocl/gemm.store_output.cl).  On TPU:

- :func:`matmul` is the framework-wide matrix multiply: casts operands to
  the policy compute dtype (bf16 feeds the MXU at full rate), accumulates
  in the policy accumulation dtype, applies the policy
  ``jax.lax.Precision``.  The reference's Kahan/multipartial
  PRECISION_LEVEL ladder maps onto that precision enum + f32 accumulation
  (documented delta: SURVEY.md §7 "Numerics parity knobs").
- :func:`pallas_matmul` is the hand-tiled path for cases XLA cannot fuse:
  an arbitrary ``epilogue`` traced into the same kernel right before the
  store — the STORE_OUTPUT capability, TPU-style.
"""

import functools

import jax
import jax.numpy as jnp

from veles_tpu import dtypes


def matmul(a, b, out_dtype=None):
    """Policy matmul: ``a @ b`` on the MXU.

    Operands cast to ``root.common.precision.compute_dtype``,
    accumulation in ``accum_dtype``, output cast to ``out_dtype`` (default
    accum dtype — callers keeping bf16 activations pass it explicitly).
    """
    cd = dtypes.compute_dtype()
    ad = dtypes.accum_dtype()
    out = jax.lax.dot_general(
        a.astype(cd), b.astype(cd),
        dimension_numbers=(((a.ndim - 1,), (0,)), ((), ())),
        precision=dtypes.matmul_precision(),
        preferred_element_type=ad)
    return out.astype(out_dtype) if out_dtype is not None else out


def _mm_kernel(a_ref, b_ref, *rest, k_steps, epilogue, precision,
               has_scale):
    """Tiled GEMM kernel body: accumulate over the K grid axis in VMEM
    scratch, run the epilogue (including the optional fused per-column
    scale — the int8 weight-only dequant) on the final step, store."""
    import jax.experimental.pallas as pl
    if has_scale:
        scale_ref, out_ref, acc_ref = rest
    else:
        out_ref, acc_ref = rest
        scale_ref = None

    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    a = a_ref[...]
    b = b_ref[...]
    if b.dtype != a.dtype:   # int8 weight tiles feed the MXU in the
        b = b.astype(a.dtype)  # activation dtype; dequant is deferred
    acc_ref[...] += jax.lax.dot_general(
        a, b,
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _store():
        acc = acc_ref[...]
        if scale_ref is not None:
            acc = acc * scale_ref[...]        # [1, bn] broadcasts
        if epilogue is not None:
            acc = epilogue(acc)
        out_ref[...] = acc.astype(out_ref.dtype)


def _pallas_matmul_body(a, b, col_scale=None, block_m=256,
                        block_n=256, block_k=512, epilogue=None,
                        out_dtype=jnp.float32, interpret=False,
                        precision=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    block_m = min(block_m, m)
    block_n = min(block_n, n)
    block_k = min(block_k, k)
    assert m % block_m == 0 and n % block_n == 0 and k % block_k == 0, \
        "shapes must tile evenly; pad first (%s @ %s)" % (a.shape, b.shape)
    if precision is None:
        # f32 operands default to exact f32 passes; bf16 operands are
        # already the policy's fast path
        precision = (jax.lax.Precision.HIGHEST
                     if a.dtype == jnp.float32 else
                     jax.lax.Precision.DEFAULT)
    k_steps = k // block_k
    grid = (m // block_m, n // block_n, k_steps)
    kernel = functools.partial(_mm_kernel, k_steps=k_steps,
                               epilogue=epilogue, precision=precision,
                               has_scale=col_scale is not None)
    in_specs = [
        pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk)),
        pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j)),
    ]
    operands = [a, b]
    if col_scale is not None:
        in_specs.append(
            pl.BlockSpec((1, block_n), lambda i, j, kk: (0, j)))
        operands.append(col_scale.reshape(1, n))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        # jax >= 0.5 renamed TPUCompilerParams -> CompilerParams
        compiler_params=getattr(
            pltpu, "CompilerParams",
            getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)


def pallas_matmul(a, b, block_m=256, block_n=256, block_k=512,
                  epilogue=None, out_dtype=jnp.float32, interpret=None,
                  precision=None, col_scale=None, backend=None):
    """Hand-tiled MXU GEMM with a fused epilogue.

    ``epilogue(acc) -> acc`` is traced into the kernel between the last
    accumulation and the store — the TPU-native STORE_OUTPUT hook
    (ref: ocl/gemm.store_output.cl usage in matrix_multiplication.cl).
    ``col_scale`` ([n] f32, optional) is a fused per-output-column
    multiply applied before ``epilogue`` — the int8 weight-only
    dequantization.  Shapes must tile evenly; callers pad (the
    framework zero-pads batches anyway for jit shape stability).

    ``interpret`` defaults to ``ops.common.use_interpret(backend)`` —
    the flash/lrn convention: off-TPU targets run the kernel under the
    pallas interpreter instead of tracing Mosaic (previously the
    default here was a hard ``False``, which left every CPU caller to
    pass ``interpret=True`` by hand or crash — the epilogue path went
    untested on tier-1)."""
    from veles_tpu.ops.common import use_interpret
    if interpret is None:
        interpret = use_interpret(backend)
    return _pallas_matmul_jit()(a, b, col_scale=col_scale,
                                block_m=block_m, block_n=block_n,
                                block_k=block_k, epilogue=epilogue,
                                out_dtype=out_dtype,
                                interpret=bool(interpret),
                                precision=precision)


from veles_tpu.telemetry import track_jit  # noqa: E402 (cycle-free: telemetry only needs logger)


@functools.lru_cache(maxsize=1)
def _pallas_matmul_jit():
    # built lazily (no module-level executable ref — the track_jit
    # lifetime note): one process-wide jitted entry, registered under
    # the stable name bench and the compile dashboards key on
    return track_jit("ops.pallas_matmul", jax.jit(
        _pallas_matmul_body,
        static_argnames=("block_m", "block_n", "block_k", "epilogue",
                         "out_dtype", "interpret", "precision")))


# -- int8 weight-only matmul ------------------------------------------------

def int8_weight_quantize(w):
    """Per-output-channel symmetric int8 weight quantization:
    ``w`` [k, n] → ``(wq int8 [k, n], scale f32 [n])`` with
    ``wq * scale ~= w`` (absmax per column; an all-zero column gets
    scale 0 and dequantizes to exact zeros)."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=0)
    scale = amax / 127.0
    q = jnp.where(scale[None, :] > 0.0,
                  wf / jnp.maximum(scale[None, :], 1e-30), 0.0)
    return jnp.clip(jnp.round(q), -127.0, 127.0).astype(jnp.int8), \
        scale.astype(jnp.float32)


def int8_matmul(a, wq, scale, out_dtype=jnp.float32, block_m=256,
                block_n=256, block_k=512, interpret=None,
                backend=None):
    """Weight-only int8 GEMM: ``a`` [m, k] (f32/bf16) times int8
    weights ``wq`` [k, n] with the per-column dequant ``scale`` [n]
    FUSED into the store epilogue — the accumulator sees raw int8
    products (full-rate MXU feed), the scale is applied once per
    output tile instead of dequantizing the whole weight matrix into
    HBM first.  Shapes that don't tile the block sizes fall back to
    an XLA dot with the same deferred-dequant math (serving buckets
    are powers of two, so the decode MLP/proj always takes the
    kernel)."""
    m, k = a.shape
    k2, n = wq.shape
    assert k == k2, (a.shape, wq.shape)
    if m % min(block_m, m) or n % min(block_n, n) \
            or k % min(block_k, k):
        acc = jax.lax.dot_general(
            a.astype(jnp.float32), wq.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return (acc * scale[None, :]).astype(out_dtype)
    return pallas_matmul(a, wq, block_m=block_m, block_n=block_n,
                         block_k=block_k, out_dtype=out_dtype,
                         interpret=interpret, col_scale=scale,
                         backend=backend)
