"""GEMM — MXU matmul under the precision policy, plus a Pallas tiled
kernel with a fused-epilogue hook.

Rebuild of ocl/matrix_multiplication*.cl (351 LoC of hand-tiled
shared-memory GEMM in 3 precision levels) and the ``STORE_OUTPUT``
epilogue-injection hook (ref: ocl/gemm.store_output.cl).  On TPU:

- :func:`matmul` is the framework-wide matrix multiply: casts operands to
  the policy compute dtype (bf16 feeds the MXU at full rate), accumulates
  in the policy accumulation dtype, applies the policy
  ``jax.lax.Precision``.  The reference's Kahan/multipartial
  PRECISION_LEVEL ladder maps onto that precision enum + f32 accumulation
  (documented delta: SURVEY.md §7 "Numerics parity knobs").
- :func:`pallas_matmul` is the hand-tiled path for cases XLA cannot fuse:
  an arbitrary ``epilogue`` traced into the same kernel right before the
  store — the STORE_OUTPUT capability, TPU-style.
"""

import functools

import jax
import jax.numpy as jnp

from veles_tpu import dtypes


def matmul(a, b, out_dtype=None):
    """Policy matmul: ``a @ b`` on the MXU.

    Operands cast to ``root.common.precision.compute_dtype``,
    accumulation in ``accum_dtype``, output cast to ``out_dtype`` (default
    accum dtype — callers keeping bf16 activations pass it explicitly).
    """
    cd = dtypes.compute_dtype()
    ad = dtypes.accum_dtype()
    out = jax.lax.dot_general(
        a.astype(cd), b.astype(cd),
        dimension_numbers=(((a.ndim - 1,), (0,)), ((), ())),
        precision=dtypes.matmul_precision(),
        preferred_element_type=ad)
    return out.astype(out_dtype) if out_dtype is not None else out


def _mm_kernel(a_ref, b_ref, out_ref, acc_ref, *, k_steps, epilogue,
               precision):
    """Tiled GEMM kernel body: accumulate over the K grid axis in VMEM
    scratch, run the epilogue on the final step, store."""
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        a_ref[...], b_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _store():
        acc = acc_ref[...]
        if epilogue is not None:
            acc = epilogue(acc)
        out_ref[...] = acc.astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_m", "block_n", "block_k", "epilogue",
                     "out_dtype", "interpret", "precision"))
def pallas_matmul(a, b, block_m=256, block_n=256, block_k=512,
                  epilogue=None, out_dtype=jnp.float32, interpret=False,
                  precision=None):
    """Hand-tiled MXU GEMM with a fused epilogue.

    ``epilogue(acc) -> acc`` is traced into the kernel between the last
    accumulation and the store — the TPU-native STORE_OUTPUT hook
    (ref: ocl/gemm.store_output.cl usage in matrix_multiplication.cl).
    Shapes must tile evenly; callers pad (the framework zero-pads batches
    anyway for jit shape stability).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    block_m = min(block_m, m)
    block_n = min(block_n, n)
    block_k = min(block_k, k)
    assert m % block_m == 0 and n % block_n == 0 and k % block_k == 0, \
        "shapes must tile evenly; pad first (%s @ %s)" % (a.shape, b.shape)
    if precision is None:
        # f32 operands default to exact f32 passes; bf16 operands are
        # already the policy's fast path
        precision = (jax.lax.Precision.HIGHEST
                     if a.dtype == jnp.float32 else
                     jax.lax.Precision.DEFAULT)
    k_steps = k // block_k
    grid = (m // block_m, n // block_n, k_steps)
    kernel = functools.partial(_mm_kernel, k_steps=k_steps,
                               epilogue=epilogue, precision=precision)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        # jax >= 0.5 renamed TPUCompilerParams -> CompilerParams
        compiler_params=getattr(
            pltpu, "CompilerParams",
            getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, b)


from veles_tpu.telemetry import track_jit  # noqa: E402 (cycle-free: telemetry only needs logger)

pallas_matmul = track_jit("ops.pallas_matmul", pallas_matmul)
