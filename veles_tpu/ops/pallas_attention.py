"""Native pallas flash-attention kernels — the framework's own
implementation of the attention hot op (the discipline SURVEY.md §2.2
demands: the reference hand-wrote its hottest kernels in OpenCL/CUDA,
e.g. ocl/forward.cl; on TPU the equivalent is a pallas program that
keeps the score blocks in VMEM instead of round-tripping the
[seq, seq] matrix through HBM).

Three kernels wired by a `jax.custom_vjp` — the standard
FlashAttention-2 decomposition:

- forward: online-softmax accumulation over K/V blocks, saving only
  the output and the per-row logsumexp;
- backward dq: recompute p block-by-block from (q, k, logsumexp),
  accumulate dq across K blocks;
- backward dk/dv: same recompute with the grid transposed (Q blocks
  innermost), accumulating dk/dv.

The sibling module `ops/flash.py` wraps the kernel that ships WITH
jax; keeping both is deliberate — the jax kernel is the battle-tested
default, this one is the in-repo implementation (selected with
``attn_impl="pallas"``), runs under ``interpret=True`` on CPU for
tests, and is the place to fuse framework-specific epilogues the
stock kernel can't express.

Layouts: kernels see [bh, seq, head_dim] (batch × heads flattened
into the leading grid dim); the public entry takes the framework's
[batch, seq, heads, head_dim].
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: 1024-token K blocks HALVE the per-block online-softmax bookkeeping
#: rounds (the m/l/acc rescale runs on lane-replicated [bq, 128]
#: scratch, so its cost rivals the matmuls at small batch×heads) —
#: measured faster than 512 at every length, and past the jax-shipped
#: kernel at 32k (32.3 vs 38.3 ms; ROUND5_NOTES.md §5)
DEFAULT_BLOCK = 1024
#: larger Q blocks amortize the K/V streaming (21% on the jax kernel
#: at head_dim 128 — ROUND4_NOTES.md)
DEFAULT_BLOCK_Q = 1024
#: finite stand-in for -inf: exp(x - max) underflows to 0 for masked
#: entries without generating nan through (-inf) - (-inf)
_NEG_INF = -1e30
#: lane width — running row-stats scratch replicates across it
_LANES = 128


from veles_tpu.ops.common import use_interpret as _use_interpret


def _mask(s, q_base, k_base, block_q, block_k, causal, kv_len):
    """Causal and/or K-length masking of a score block.  ``kv_len``
    is the REAL key length — block-padded tail columns (the
    pad-and-mask entry for odd sequence lengths) mask away here."""
    rows = q_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = k_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = cols < kv_len
    if causal:
        keep &= cols <= rows
    return jnp.where(keep, s, _NEG_INF)


def _masked_scores(s, q_base, k_base, block_q, block_k, causal,
                   kv_len):
    """Apply causal/tail masking to a score block, but make the
    masking straight-line (see the note below)."""
    tail = kv_len % block_k != 0      # static: padded K tail exists
    if not causal and not tail:
        return s
    # NOTE a lax.cond that skips the mask on sub-diagonal blocks was
    # measured SLOWER at every length (12.0 vs 11.4 ms at seq 2048,
    # 55.7 vs 42.3 at 32k) — Mosaic's branch disrupts the pipeline
    # more than the unconditional mask costs; keep it straight-line
    return _mask(s, q_base, k_base, block_q, block_k, causal, kv_len)


def _clamp_maps(block_q, block_k, causal):
    """Index maps for the K/V streams of a (bh, q, k) grid.  For the
    causal case the K index CLAMPS to the diagonal block: grid steps
    past the diagonal re-request the same block, and pallas skips the
    DMA for a repeated index — causally dead K/V blocks are never
    fetched (the r4 gap vs the jax kernel at long context:
    ROUND4_NOTES.md §1b named this as the next step)."""
    if not causal:
        return lambda b, i, j: (b, j, 0)

    def kv_map(b, i, j):
        j_max = ((i + 1) * block_q - 1) // block_k
        return (b, jnp.minimum(j, j_max), 0)

    return kv_map


def _clamp_maps_dkv(block_q, block_k, causal):
    """Index maps for the Q/dO/O/lse streams of a (bh, k, q) grid:
    the Q index clamps UP to the first block at-or-past the diagonal,
    so leading dead steps re-request that block (one DMA, no more)."""
    if not causal:
        return lambda b, i, j: (b, j, 0)

    def q_map(b, i, j):
        j_min = (i * block_k) // block_q
        return (b, jnp.maximum(j, j_min), 0)

    return q_map


# -- forward ----------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal,
                block_q, block_k, kv_len):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    q_base = pl.program_id(1) * block_q
    k_base = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _block():
        # operands stay in the input dtype (bf16 feeds the MXU at
        # full rate); accumulation is f32 via preferred_element_type
        q = q_ref[0]                              # [bq, d]
        k = k_ref[0]                              # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        s = _masked_scores(s, q_base, k_base, block_q, block_k,
                           causal, kv_len)
        m_prev = m_ref[:, 0]                      # [bq]
        m_cur = jnp.maximum(m_prev, s.max(axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])           # [bq, bk]
        l_cur = l_ref[:, 0] * alpha + p.sum(axis=1)
        m_ref[...] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur[:, None], l_ref.shape)
        v = v_ref[0]                              # [bk, dv]
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot(
            p.astype(v.dtype), v,
            preferred_element_type=jnp.float32)

    if causal:
        # K blocks strictly above the diagonal band contribute nothing
        @pl.when(k_base <= q_base + block_q - 1)
        def _():
            _block()
    else:
        _block()

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)
        # lane-replicated (the Mosaic-friendly layout for per-row
        # scalars — block last-dims must tile (8, 128))
        lse = m_ref[:, 0] + jnp.log(l)
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _run_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
             kv_len):
    """q/k/v: [bh, seq, d] (block-padded) → (o [bh, sq, dv],
    lse [bh, sq, 128] f32 lane-replicated); ``kv_len`` = real key
    length for tail masking."""
    bh, sq, d = q.shape
    sk, dv = k.shape[1], v.shape[2]
    kv_map = _clamp_maps(block_q, block_k, causal)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          kv_len=kv_len),
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, dv), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dv), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, dv), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, dv), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# -- backward ---------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                   dq_ref, acc_ref, *, scale, causal, block_q,
                   block_k, kv_len):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    q_base = pl.program_id(1) * block_q
    k_base = ki * block_k

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _block():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        # D = rowsum(dO ⊙ O) recomputed per block (cheaper than a
        # lane-replicated HBM side array)
        delta = jnp.sum(do.astype(jnp.float32)
                        * o_ref[0].astype(jnp.float32), axis=-1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _masked_scores(s, q_base, k_base, block_q, block_k,
                           causal, kv_len)
        p = jnp.exp(s - lse_ref[0][:, 0][:, None])    # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, bk]
        ds = p * (dp - delta[:, None]) * scale
        acc_ref[...] += jax.lax.dot(
            ds.astype(k.dtype), k,
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(k_base <= q_base + block_q - 1)
        def _():
            _block()
    else:
        _block()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, scale,
                    causal, block_q, block_k, kv_len):
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    q_base = qi * block_q
    k_base = pl.program_id(1) * block_k

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    def _block():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        delta = jnp.sum(do.astype(jnp.float32)
                        * o_ref[0].astype(jnp.float32), axis=-1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _masked_scores(s, q_base, k_base, block_q, block_k,
                           causal, kv_len)
        p = jnp.exp(s - lse_ref[0][:, 0][:, None])
        dv_acc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bk, dv]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_acc_ref[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bk, d]

    if causal:
        @pl.when(k_base <= q_base + block_q - 1)
        def _():
            _block()
    else:
        _block()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


# -- custom_vjp wiring ------------------------------------------------------

@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _mha(q, k, v, scale, causal, block_q, block_k, interpret, kv_len):
    o, _ = _mha_fwd(q, k, v, scale, causal, block_q, block_k,
                    interpret, kv_len)
    return o


def _mha_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
             kv_len):
    o, lse = _run_fwd(q, k, v, scale, causal, block_q, block_k,
                      interpret, kv_len)
    return o, (q, k, v, o, lse)


def _mha_bwd(scale, causal, block_q, block_k, interpret, kv_len, res,
             do):
    q, k, v, o, lse = res
    bh, sq, d = q.shape
    sk, dv = k.shape[1], v.shape[2]

    kv_map = _clamp_maps(block_q, block_k, causal)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          kv_len=kv_len),
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, dv), kv_map),
            pl.BlockSpec((1, block_q, dv), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, dv), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, o, lse)

    q_map = _clamp_maps_dkv(block_q, block_k, causal)
    dk, dv_out = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          kv_len=kv_len),
        grid=(bh, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dv), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, dv), q_map),
            pl.BlockSpec((1, block_q, dv), q_map),
            pl.BlockSpec((1, block_q, _LANES), q_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dv), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, dv), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, dv), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, o, lse)
    return dq, dk, dv_out


_mha.defvjp(_mha_fwd, _mha_bwd)


def pallas_attention(q, k, v, causal=False, scale=None,
                     block_q=None, block_k=DEFAULT_BLOCK,
                     backend=None):
    """Exact attention via the native pallas kernels.  q/k/v:
    [batch, seq, heads, head_dim] (framework layout).  ANY sequence
    length runs the fast path (odd lengths pad-and-mask to block
    multiples in-kernel); head_dim should be a lane multiple for
    real-hardware performance.  Causally dead K/V blocks are never
    FETCHED (clamped index maps — pallas skips the DMA on a repeated
    block index), so long-context cost scales with the triangle, not
    the square.  ``backend`` is the platform of the TARGET device
    (see ops.common.use_interpret) — callers that know their device
    must pass it (ADVICE.md r4 #1)."""
    b, sq, h, d = q.shape
    sk, dv = k.shape[1], v.shape[3]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if block_q is None:
        block_q = DEFAULT_BLOCK_Q
    bq = min(block_q, max(sq, 16))
    bk = min(block_k, max(sk, 16))
    # pad-and-mask (VERDICT r4 #7): odd sequence lengths keep the
    # fast path — Q/K/V zero-pad up to block multiples, the kernels
    # mask tail K columns via kv_len, and the output slices back.
    # Zero-padded Q rows produce garbage outputs that are sliced
    # away, and their backward contributions vanish because the
    # padded cotangent rows are zero.
    sq_p = -(-sq // bq) * bq
    sk_p = -(-sk // bk) * bk

    def flat(t, seq_to):
        t = jnp.swapaxes(t, 1, 2).reshape(b * h, t.shape[1],
                                          t.shape[3])
        if t.shape[1] != seq_to:
            t = jnp.pad(t, ((0, 0), (0, seq_to - t.shape[1]), (0, 0)))
        return t

    o = _mha(flat(q, sq_p), flat(k, sk_p), flat(v, sk_p),
             float(scale), bool(causal), bq, bk,
             _use_interpret(backend), sk)
    o = o[:, :sq]
    return jnp.swapaxes(o.reshape(b, h, sq, dv), 1, 2)
