"""Flash attention — the pallas TPU kernel path for the attention hot
op (SURVEY.md §5 "Long-context"; the reference's hottest ops were
hand-written CUDA/OpenCL kernels, e.g. ocl/forward.cl — on TPU the
equivalent discipline is a pallas kernel that keeps the score blocks
in VMEM instead of round-tripping the [seq, seq] matrix through HBM).

The kernel itself is ``jax.experimental.pallas.ops.tpu.flash_attention``
(a pallas_call program with custom fwd/dq/dkv kernels, shipped with
JAX the way cuDNN ships with CUDA); this module owns the framework's
integration: the [batch, seq, heads, head_dim] layout adaptation, the
block-size tuning that measured 2.6x over the kernel's defaults on
TPU v5e (1024-token Q blocks over 512-token K blocks, dropping to
uniform 512 when seq doesn't divide 1024; see ROUND4_NOTES.md), the
applicability check, and the numerically-equivalent streaming fallback
(ops.attention.blockwise_attention) for CPU meshes and odd shapes so
tests and virtual-device dryruns run the same model code."""

import functools

import jax
import jax.numpy as jnp

#: the kernel wants block-aligned tiles; Q blocks of 1024 over K
#: blocks of 512 measured fastest at head_dim 128 on TPU v5e
#: (21% over uniform 512 at seq 2048 / 16 heads; ROUND4_NOTES.md) —
#: the applicability gate stays at the K granularity
_BLOCK_Q = 1024
_BLOCK = 512


def flash_available(q_shape, backend=None):
    """True when the pallas TPU kernel applies: TPU backend, seq a
    multiple of the block, head_dim a lane multiple.

    ``backend`` should be the platform of the device the computation
    actually targets (callers inside a unit pass
    ``unit.device.jax_device.platform``) — the process default backend
    is only a last resort, since a CPU-compiled program on a TPU host
    must NOT trace the TPU kernel."""
    if backend is None:
        backend = jax.default_backend()
    if backend not in ("tpu", "axon"):
        return False
    seq, hd = q_shape[-3], q_shape[-1]
    return seq % _BLOCK == 0 and hd % 128 == 0


@functools.lru_cache(maxsize=None)
def _block_sizes(seq):
    from jax.experimental.pallas.ops.tpu import flash_attention as fa
    # the kernel's backward pass REQUIRES seq divisible by the q
    # block — a 512-but-not-1024 multiple (1536, 2560, …) drops to
    # the uniform 512 config the applicability gate guarantees
    bq = _BLOCK_Q if seq % _BLOCK_Q == 0 else _BLOCK
    bq = min(bq, seq)
    bk = min(_BLOCK, seq)
    return fa.BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq)


def flash_attention(q, k, v, causal=False, scale=None, backend=None):
    """Exact attention via the pallas TPU kernel.  q/k/v:
    [batch, seq, heads, head_dim] (the framework layout — seq-major so
    sp sharding stays a leading-dim spec); falls back to the streaming
    blockwise op when the kernel doesn't apply.  ``backend`` is the
    TARGET device platform (see :func:`flash_available`) — callers
    that know their device must pass it, or a CPU-compiled program on
    a TPU host would trace the TPU kernel."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if not flash_available(q.shape, backend=backend):
        from veles_tpu.ops.attention import blockwise_attention
        return blockwise_attention(q, k, v, block_size=_BLOCK,
                                   causal=causal, scale=scale)
    from jax.experimental.pallas.ops.tpu import flash_attention as fa
    qt, kt, vt = (jnp.swapaxes(t, -3, -2) for t in (q, k, v))
    o = fa.flash_attention(qt, kt, vt, causal=causal, sm_scale=scale,
                           block_sizes=_block_sizes(q.shape[-3]))
    return jnp.swapaxes(o, -3, -2)
