"""Cross-channel LRN as a banded matmul — the AlexNet hot op.

    y = x / (k + alpha * sum_{j in window(c)} x_j^2) ** beta

Formulation log (every number measured on the full AlexNet train step,
TPU v5e, batch 1024, jax.profiler XLA-op timeline — isolated
micro-benchmarks of this op actively mislead, the fusion context
dominates):

- banded C×C matmul, plain autodiff (THIS file): 15.4k samples/sec
- shifted adds / ``reduce_window`` on the VPU:   12.1-12.7k (the
  cross-lane rotations schedule as extra HBM round trips)
- Pallas kernels (pad-shift / roll / in-kernel band): 5.9k best —
  lane rotations in Mosaic ran far below HBM speed at C=96
- custom-VJP band (recompute denominator):       13.5k — the whole
  minibatch step is ONE XLA program, so autodiff's "saved" forward
  product is CSE-shared for free and recompute just adds a matmul
- band + ``optimization_barrier`` isolation:     13.8-14.7k — XLA's
  own fusion choices beat hand-drawn fusion boundaries

The remaining known waste: the backward transposed band dot picks
XLA's batch-in-sublanes convolution emitter (~3x the forward's
batch-in-lanes schedule).  None of the tricks above flips it without
losing more elsewhere; revisit when XLA's emitter heuristics change.

ROUND 5 ADDENDUM — ``lrn_pallas`` below: a pallas kernel pair
(forward + recompute-backward under ``jax.custom_vjp``) that does the
band product ON THE MXU inside the kernel (never a cross-lane rotate,
the r2 attempts' mistake), with narrow channel counts packed to lane
multiples (``_pack_group``).  Measured on TPU v5e at the AlexNet
shapes it beats the band formulation IN ISOLATION (9.2 vs 13.3 ms at
[1024·55·55, 96] fwd+bwd, 5.7 vs 8.8 at [1024·27·27, 256]) — but
LOSES in the full train step, because the graph-level [B,55,55,96] →
[R,C] flatten is a tiled-layout change XLA must materialize (W=55 is
not a sublane multiple), costing ~1.8 ms per crossing, four crossings
per layer-pass; the r5 full-step A/B measured 15.2k (band) vs 9.9k
(pallas) samples/s.  A fused LRN+maxpool kernel prototype (per-sample
blocks, in-VMEM W-padding, H-pool via free leading-dim reshapes,
W-pool via a 2·C lane fold) reached parity-to-slightly-better on the
forward (5.7 vs 6.7 ms) but its backward is VPU-pointwise-bound at
the same ~10 ms the XLA backward already costs: Mosaic DMA streams
cap at ~330 GB/s aggregate on this chip (measured; XLA fusions reach
~660), and the EUP is f32-only, so the kernel cannot beat the fused
XLA loops on a streaming-plus-transcendental op.  Full experiment
log: ROUND5_NOTES.md.  The band formulation therefore REMAINS the
production TPU path; ``lrn_pallas`` ships tested as the in-repo
native-kernel counterpart (SURVEY §2.2) and the decision record.
"""

import functools

import jax
import jax.numpy as jnp
import numpy
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


@functools.lru_cache(maxsize=None)
def _band(c, n):
    """band[src, dst] = 1 iff channel ``src`` is inside ``dst``'s
    window [dst-half, dst+n-1-half] (reduce_window semantics with
    (half, n-1-half) padding).  Cached as NUMPY — a cached jax array
    created under a trace would leak the tracer across jit scopes."""
    half = n // 2
    src = numpy.arange(c)[:, None]
    dst = numpy.arange(c)[None, :]
    b = ((dst - src) <= half) & ((src - dst) <= (n - 1 - half))
    return b.astype(numpy.float32)


def _band_dot(t, c, n):
    """[..., C] @ band with f32 accumulation."""
    band = jnp.asarray(_band(c, n), t.dtype)
    return jax.lax.dot_general(
        t, band, (((t.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _power(s, beta):
    if beta == 0.75:
        # s^-0.75 = rsqrt(s)·sqrt(rsqrt(s)): cheap VPU ops (lax.pow
        # lowers to exp/log)
        r = jax.lax.rsqrt(s)
        return r * jnp.sqrt(r)
    return jax.lax.pow(s, -beta)


def lrn(x, alpha=1e-4, beta=0.75, n=5, k=2.0):
    """LRN over the last (channel) axis of ``x``.

    Plain autodiff band matmul: the whole minibatch step is one XLA
    program, so the forward band product is CSE-shared with the
    backward, and XLA's own fusion choices measured faster than every
    alternative tried (custom-VJP recompute, optimization_barrier
    isolation, reduce_window, shifted adds, three Pallas kernels —
    each benchmarked on the full AlexNet step, see the module
    docstring)."""
    c = x.shape[-1]
    sq = x * x
    # the downcast of the window sum to x.dtype is DELIBERATE: with
    # bf16 activations it keeps the saved denominator chain bf16,
    # which measured 4% faster end-to-end than carrying f32 (the
    # denominator is k-dominated, so bf16 rounding of the sum is
    # harmless — convergence suites pass either way)
    ssum = _band_dot(sq, c, n).astype(x.dtype)
    s = k + alpha * ssum.astype(jnp.float32)
    return (x.astype(jnp.float32) * _power(s, beta)).astype(x.dtype)


# -- fused pallas kernels ---------------------------------------------------
#
# One grid dimension over row blocks of the [R, C] flattening
# (R = batch x spatial).  The channel window is an in-VMEM [C, C]
# band matmul on the MXU — never a cross-lane rotate.  The backward
# recomputes the denominator from x (two more tiny band dots) instead
# of saving it, so the residual is just x and each pass is exactly
# one HBM read + one write.
#
# ROW PACKING: narrow channel counts stream badly (a width-96 block
# measured 4.84 ms for a pure copy of 0.59 GB vs 3.57 at width 1536 —
# Mosaic DMA pays for partial lanes).  ``_pack_group`` folds g
# consecutive rows into one width-g·C row (a FREE reshape — row-major
# bytes are unchanged) and the band becomes a [g·C, g·C] block
# diagonal, so every row of the packed block is g independent LRN
# windows and the lane dim is a 128-multiple.

#: rows per block: 1024 x 256ch x bf16 = 512 KB/block — three
#: double-buffered streams (x, dy, dx) fit VMEM with headroom
_BLOCK_ROWS = 1024


_LANES = 128


def _pack_group(c):
    """Smallest g with g*c a lane multiple (capped — the [g*c, g*c]
    band and the f32 intermediates must stay VMEM-friendly)."""
    g = 1
    while (g * c) % _LANES and g * c < 1024:
        g += 1
    return g if (g * c) % _LANES == 0 else 1


@functools.lru_cache(maxsize=None)
def _band_packed(c, n, g):
    """Block-diagonal [g*c, g*c] band: g independent channel windows."""
    b = _band(c, n)
    out = numpy.zeros((g * c, g * c), numpy.float32)
    for i in range(g):
        out[i * c:(i + 1) * c, i * c:(i + 1) * c] = b
    return out


def _lrn_fwd_kernel(x_ref, band_ref, y_ref, *, alpha, beta, k):
    x = x_ref[...]
    ssum = jax.lax.dot(x * x, band_ref[...],
                       preferred_element_type=jnp.float32)
    s = k + alpha * ssum
    y_ref[...] = (x.astype(jnp.float32)
                  * _power(s, beta)).astype(y_ref.dtype)


def _lrn_bwd_kernel(x_ref, dy_ref, band_ref, dx_ref, *, alpha, beta, k):
    xb = x_ref[...]
    band = band_ref[...]
    ssum = jax.lax.dot(xb * xb, band,
                       preferred_element_type=jnp.float32)
    s = k + alpha * ssum
    p = _power(s, beta)                      # s^-beta, f32
    x = xb.astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    t = dy * x * (p / s)                     # dy·x·s^(-beta-1)
    # u_i = sum_c band[i, c] t_c  ==  t @ band^T
    u = jax.lax.dot_general(
        t.astype(xb.dtype), band, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dx = dy * p - (2.0 * alpha * beta) * x * u
    dx_ref[...] = dx.astype(dx_ref.dtype)


from veles_tpu.ops.common import use_interpret as _pallas_interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _lrn_rows(x, c, alpha, beta, n, k, interpret):
    """x: [R, W] with W = g*c (g packed windows per row)."""
    y, _ = _lrn_rows_fwd(x, c, alpha, beta, n, k, interpret)
    return y


def _band_arg(c, n, g, dtype):
    # 0/1 entries are exact in bf16, so the band feeds the MXU in the
    # activation dtype at full rate
    return jnp.asarray(_band_packed(c, n, g), dtype)


def _lrn_rows_fwd(x, c, alpha, beta, n, k, interpret):
    r, w = x.shape
    y = pl.pallas_call(
        functools.partial(_lrn_fwd_kernel, alpha=alpha, beta=beta, k=k),
        grid=(pl.cdiv(r, _BLOCK_ROWS),),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, w), lambda i: (i, 0)),
            pl.BlockSpec((w, w), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, w), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((r, w), x.dtype),
        interpret=interpret,
    )(x, _band_arg(c, n, w // c, x.dtype))
    return y, (x,)


def _lrn_rows_bwd(c, alpha, beta, n, k, interpret, res, dy):
    (x,) = res
    r, w = x.shape
    dx = pl.pallas_call(
        functools.partial(_lrn_bwd_kernel, alpha=alpha, beta=beta, k=k),
        grid=(pl.cdiv(r, _BLOCK_ROWS),),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, w), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, w), lambda i: (i, 0)),
            pl.BlockSpec((w, w), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, w), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((r, w), x.dtype),
        interpret=interpret,
    )(x, dy.astype(x.dtype), _band_arg(c, n, w // c, x.dtype))
    return (dx,)


_lrn_rows.defvjp(_lrn_rows_fwd, _lrn_rows_bwd)


def lrn_pallas(x, alpha=1e-4, beta=0.75, n=5, k=2.0, backend=None):
    """LRN over the last axis via the fused pallas kernel pair
    (differentiable — backward is its own fused kernel).

    ``backend`` is the platform of the TARGET device (callers inside a
    unit pass ``unit.device.jax_device.platform``); off-TPU the same
    kernels run under ``interpret=True`` so CPU tests exercise the
    real code path."""
    c = x.shape[-1]
    rows = x.reshape(-1, c)
    g = _pack_group(c)
    if g > 1 and rows.shape[0] % g == 0:
        rows = rows.reshape(-1, g * c)
    y = _lrn_rows(rows, int(c), float(alpha), float(beta), int(n),
                  float(k), _pallas_interpret(backend))
    return y.reshape(x.shape)
