"""Cross-channel LRN as a banded matmul — the AlexNet hot op.

    y = x / (k + alpha * sum_{j in window(c)} x_j^2) ** beta

Formulation log (every number measured on the full AlexNet train step,
TPU v5e, batch 1024, jax.profiler XLA-op timeline — isolated
micro-benchmarks of this op actively mislead, the fusion context
dominates):

- banded C×C matmul, plain autodiff (THIS file): 15.4k samples/sec
- shifted adds / ``reduce_window`` on the VPU:   12.1-12.7k (the
  cross-lane rotations schedule as extra HBM round trips)
- Pallas kernels (pad-shift / roll / in-kernel band): 5.9k best —
  lane rotations in Mosaic ran far below HBM speed at C=96
- custom-VJP band (recompute denominator):       13.5k — the whole
  minibatch step is ONE XLA program, so autodiff's "saved" forward
  product is CSE-shared for free and recompute just adds a matmul
- band + ``optimization_barrier`` isolation:     13.8-14.7k — XLA's
  own fusion choices beat hand-drawn fusion boundaries

The remaining known waste: the backward transposed band dot picks
XLA's batch-in-sublanes convolution emitter (~3x the forward's
batch-in-lanes schedule).  None of the tricks above flips it without
losing more elsewhere; revisit when XLA's emitter heuristics change.
"""

import functools

import jax
import jax.numpy as jnp
import numpy


@functools.lru_cache(maxsize=None)
def _band(c, n):
    """band[src, dst] = 1 iff channel ``src`` is inside ``dst``'s
    window [dst-half, dst+n-1-half] (reduce_window semantics with
    (half, n-1-half) padding).  Cached as NUMPY — a cached jax array
    created under a trace would leak the tracer across jit scopes."""
    half = n // 2
    src = numpy.arange(c)[:, None]
    dst = numpy.arange(c)[None, :]
    b = ((dst - src) <= half) & ((src - dst) <= (n - 1 - half))
    return b.astype(numpy.float32)


def _band_dot(t, c, n):
    """[..., C] @ band with f32 accumulation."""
    band = jnp.asarray(_band(c, n), t.dtype)
    return jax.lax.dot_general(
        t, band, (((t.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _power(s, beta):
    if beta == 0.75:
        # s^-0.75 = rsqrt(s)·sqrt(rsqrt(s)): cheap VPU ops (lax.pow
        # lowers to exp/log)
        r = jax.lax.rsqrt(s)
        return r * jnp.sqrt(r)
    return jax.lax.pow(s, -beta)


def lrn(x, alpha=1e-4, beta=0.75, n=5, k=2.0):
    """LRN over the last (channel) axis of ``x``.

    Plain autodiff band matmul: the whole minibatch step is one XLA
    program, so the forward band product is CSE-shared with the
    backward, and XLA's own fusion choices measured faster than every
    alternative tried (custom-VJP recompute, optimization_barrier
    isolation, reduce_window, shifted adds, three Pallas kernels —
    each benchmarked on the full AlexNet step, see the module
    docstring)."""
    c = x.shape[-1]
    sq = x * x
    # the downcast of the window sum to x.dtype is DELIBERATE: with
    # bf16 activations it keeps the saved denominator chain bf16,
    # which measured 4% faster end-to-end than carrying f32 (the
    # denominator is k-dominated, so bf16 rounding of the sum is
    # harmless — convergence suites pass either way)
    ssum = _band_dot(sq, c, n).astype(x.dtype)
    s = k + alpha * ssum.astype(jnp.float32)
    return (x.astype(jnp.float32) * _power(s, beta)).astype(x.dtype)
