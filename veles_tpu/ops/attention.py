"""Attention ops — single-chip flash-style attention and RING attention
for sequence/context parallelism (SURVEY.md §5 "Long-context": the
reference had no sequence dimension at all; the rebuild makes the ``sp``
mesh axis first-class so long contexts shard like any other dim).

Ring attention: Q stays put, K/V blocks rotate around the ``sp`` axis
via ``ppermute`` (ICI neighbour exchange), with an online-softmax
accumulator (running max + normalizer) so the result is EXACTLY
softmax(QK^T/sqrt(d))V over the full sequence while each chip only ever
holds 1/sp of K/V — the standard blockwise/ring formulation."""

import functools

import jax
import jax.numpy as jnp


def _pvary(x, axis_name):
    """Mark a fresh (axis-invariant) value as varying over axis_name —
    pcast on new JAX, pvary fallback on older releases."""
    try:
        return jax.lax.pcast(x, axis_name, to="varying")
    except (AttributeError, TypeError):
        return jax.lax.pvary(x, (axis_name,))


def attention(q, k, v, causal=False, scale=None):
    """Reference attention on one chip.  q/k/v: [..., seq, heads, dim]
    (seq-major layout keeps the sp sharding a leading-dim spec)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d)
    # [..., heads, seq_q, seq_k]
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
    if causal:
        seq_q, seq_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((seq_q, seq_k), bool),
                        seq_k - seq_q)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("...hqk,...khd->...qhd", probs, v)


def _block_contrib(q, k, v, scale, mask=None):
    """One K/V block's unnormalized contribution: (max, sumexp,
    weighted-V) per query."""
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)                       # [..., h, q]
    # guard fully-masked rows (exp(-inf - -inf) = nan)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(logits - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    s = jnp.sum(p, axis=-1)                            # [..., h, q]
    o = jnp.einsum("...hqk,...khd->...qhd", p, v)
    return m_safe, s, o


def _online_merge(acc, new):
    """Merge two partial softmax accumulators (the flash-attention
    update rule)."""
    m_a, s_a, o_a = acc
    m_b, s_b, o_b = new
    m = jnp.maximum(m_a, m_b)
    ca = jnp.exp(m_a - m)
    cb = jnp.exp(m_b - m)
    s = s_a * ca + s_b * cb
    # coefficients are [..., h, q]; outputs are [..., q, h, d]
    o = o_a * jnp.moveaxis(ca, -2, -1)[..., None] \
        + o_b * jnp.moveaxis(cb, -2, -1)[..., None]
    return m, s, o


def blockwise_attention(q, k, v, block_size=512, causal=False,
                        scale=None):
    """Exact attention WITHOUT materializing the [seq_q, seq_k] score
    matrix: a ``lax.scan`` over K/V blocks with the same online-softmax
    accumulator the ring uses — the single-chip half of the long-context
    story (the ring shards across chips; this streams within one).

    q/k/v: [..., seq, heads, dim].  Peak memory is O(seq_q ·
    block_size) per head instead of O(seq_q · seq_k).  K/V sequence
    lengths that don't divide ``block_size`` are zero-padded and
    masked.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d)
    seq_q = q.shape[-3]
    seq_k = k.shape[-3]
    bs = min(block_size, seq_k)
    pad = (-seq_k) % bs
    if pad:
        widths = [(0, 0)] * k.ndim
        widths[-3] = (0, pad)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    blocks = (seq_k + pad) // bs
    # [..., seq, h, d] -> [blocks, ..., bs, h, d] (scan axis leads)
    kb = jnp.moveaxis(
        k.reshape(k.shape[:-3] + (blocks, bs) + k.shape[-2:]), -4, 0)
    vb = jnp.moveaxis(
        v.reshape(v.shape[:-3] + (blocks, bs) + v.shape[-2:]), -4, 0)
    q_pos = jnp.arange(seq_q)

    def body(acc, blk):
        k_blk, v_blk, idx = blk
        if causal:
            k_pos = idx * bs + jnp.arange(bs)
            mask = (k_pos < seq_k)[None, None, :] & (
                k_pos[None, None, :] <=
                q_pos[None, :, None] + (seq_k - seq_q))
        elif pad:
            k_pos = idx * bs + jnp.arange(bs)
            mask = jnp.broadcast_to((k_pos < seq_k)[None, None, :],
                                    (1, seq_q, bs))
        else:
            mask = None  # unmasked hot path: no where/select traffic
        contrib = _block_contrib(q, k_blk, v_blk, scale, mask)
        # the running sum accumulates up to seq_k exp terms — carry it
        # in f32 even when activations are bf16 (the compounding merge
        # error would otherwise grow with sequence length)
        contrib = tuple(t.astype(jnp.float32) for t in contrib)
        return _online_merge(acc, contrib), None

    heads = q.shape[-2]
    batchish = q.shape[:-3]
    # the output inherits v's value dim (may differ from q/k's key dim)
    acc0 = (jnp.full(batchish + (heads, seq_q), -jnp.inf, jnp.float32),
            jnp.zeros(batchish + (heads, seq_q), jnp.float32),
            jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32))
    acc, _ = jax.lax.scan(body, acc0,
                          (kb, vb, jnp.arange(blocks)))
    m, s, o = acc
    denom = jnp.moveaxis(jnp.maximum(s, 1e-30), -2, -1)[..., None]
    return (o / denom).astype(q.dtype)


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Attention with K/V sharded over the ``axis_name`` mesh axis.

    Call under ``shard_map`` with q/k/v sharded on their sequence dim
    over ``axis_name`` (layout [seq_shard, heads, dim] per device).
    K/V rotate through every device; the online-softmax accumulator
    makes the result exact.  ``causal`` masks by GLOBAL sequence
    position (each shard owns a contiguous sequence slice)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d)
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    seq_q = q.shape[-3]
    seq_k = k.shape[-3]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def mask_for(kv_idx):
        if not causal:
            return None
        q_pos = my_idx * seq_q + jnp.arange(seq_q)       # global rows
        k_pos = kv_idx * seq_k + jnp.arange(seq_k)
        return (k_pos[None, :] <= q_pos[:, None])[None]  # [1, q, k]

    def body(carry, _):
        acc, kv, kv_idx = carry
        k_blk, v_blk = kv
        contrib = _block_contrib(q, k_blk, v_blk, scale,
                                 mask_for(kv_idx))
        # f32 accumulator: see blockwise_attention
        contrib = tuple(t.astype(jnp.float32) for t in contrib)
        acc = _online_merge(acc, contrib)
        kv = jax.lax.ppermute(kv, axis_name, perm)
        kv_idx = jax.lax.ppermute(kv_idx, axis_name, perm)
        return (acc, kv, kv_idx), None

    # derive the accumulators FROM q so they inherit q's varying-axes
    # under shard_map (a dp x sp mesh makes the carry vary over BOTH
    # axes; a fresh jnp.zeros would be axis-invariant and trip the
    # scan carry vma check)
    hs0 = jnp.swapaxes(q, -3, -2)[..., 0].astype(jnp.float32) * 0
    m0 = hs0 - jnp.inf                     # [..., heads, seq_q]
    s0 = hs0
    # the output inherits v's value dim (may differ from q/k's key dim)
    o0 = q[..., :1].astype(jnp.float32) * jnp.zeros(
        (v.shape[-1],), jnp.float32)
    (acc, _, _), _ = jax.lax.scan(
        body, ((m0, s0, o0), (k, v), my_idx), None, length=n)
    m, s, o = acc
    denom = jnp.moveaxis(jnp.maximum(s, 1e-30), -2, -1)[..., None]
    return (o / denom).astype(q.dtype)


def ring_attention_sharded(mesh, q, k, v, axis="sp", causal=False):
    """Convenience wrapper: shard q/k/v's sequence dim over ``axis`` and
    run :func:`ring_attention` under shard_map.  q/k/v: [seq, heads,
    dim] global arrays."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5 keeps it in experimental
        from jax.experimental.shard_map import shard_map

    spec = P(axis, None, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
