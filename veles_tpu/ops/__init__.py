"""ops — the kernel layer (rebuild of cuda/ + ocl/).

Every kernel the reference shipped as OpenCL/CUDA source has a TPU-native
equivalent here: XLA-traced jnp/lax ops where the compiler already does
the right thing, and Pallas kernels where fusion control matters
(SURVEY.md §2.2):

- :mod:`veles_tpu.ops.gemm`      — policy matmul + Pallas tiled GEMM with
  fused epilogue hook (ref: ocl/matrix_multiplication*.cl, gemm.cl)
- :mod:`veles_tpu.ops.normalize` — mean/dispersion normalizer
  (ref: ocl/mean_disp_normalizer.cl)
- :mod:`veles_tpu.ops.join`      — N-input concat (ref: ocl/join.jcl)
- :mod:`veles_tpu.ops.random`    — device PRNG fill (ref: ocl/random.cl)
"""

from veles_tpu.ops.gemm import matmul  # noqa: F401
from veles_tpu.ops.join import InputJoiner  # noqa: F401
from veles_tpu.ops.normalize import MeanDispNormalizer  # noqa: F401
from veles_tpu.ops.random import Uniform  # noqa: F401
