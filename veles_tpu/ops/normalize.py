"""MeanDispNormalizer — ``out = (x - mean) * rdisp``.

Rebuild of veles/mean_disp_normalizer.py:50-138 and its kernels
(ocl/mean_disp_normalizer.cl:1-20, cuda/mean_disp_normalizer.cu).  On TPU
this is a single traced elementwise expression that XLA fuses into
whatever consumes it — there is deliberately no hand-written kernel.
"""

import numpy

from veles_tpu import dtypes
from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.units import MissingDemand


def mean_disp_normalize(x, mean, rdisp, out_dtype=None):
    """The traced op — broadcast over leading (batch) dims."""
    out = (x - mean) * rdisp
    return out.astype(out_dtype or dtypes.compute_dtype())


class MeanDispNormalizer(AcceleratedUnit):
    """Unit form (ref: veles/mean_disp_normalizer.py:50): normalizes
    ``input`` with per-feature ``mean`` and reciprocal dispersion
    ``rdisp``, writing ``output`` in the compute dtype."""

    READS = ("input", "mean", "rdisp")
    WRITES = ("output",)

    def __init__(self, workflow, **kwargs):
        super(MeanDispNormalizer, self).__init__(workflow, **kwargs)
        self.input = None
        self.mean = None
        self.rdisp = None
        self.output = Array()
        self.demand("input", "mean", "rdisp")

    def initialize(self, device=None, **kwargs):
        if not all(isinstance(getattr(self, a, None), Array) and
                   bool(getattr(self, a))
                   for a in ("input", "mean", "rdisp")):
            raise MissingDemand(self, {"input", "mean", "rdisp"})
        out_dt = dtypes.as_numpy_dtype(dtypes.compute_dtype())
        self.output.reset(numpy.zeros(self.input.shape, out_dt))
        super(MeanDispNormalizer, self).initialize(device=device, **kwargs)

    def step(self, input, mean, rdisp):
        return {"output": mean_disp_normalize(input, mean, rdisp)}
