"""Device-side PRNG fill (rebuild of ocl/random.cl, cuda/random.cu and
the veles/prng/uniform.py unit).

The reference ran a xorshift1024* kernel filling a buffer with random
bits for dropout masks.  Two TPU-native paths:

- :class:`Uniform` (the unit) draws with threefry *keys-as-data*: the
  per-run key is an input tensor, so the traced step stays pure and every
  draw is reproducible from the framework RNG — this is the default.
- :func:`pallas_uniform` is the raw hardware-PRNG kernel
  (``pltpu.prng_random_bits``) for hot fused kernels (e.g. in-kernel
  dropout masks) where key plumbing is overhead; :func:`uniform` picks it
  automatically on TPU when given a plain int seed.
"""

import functools

import jax
import jax.numpy as jnp
import numpy

from veles_tpu import prng
from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.telemetry import track_jit as _track_jit


def _pallas_uniform_kernel(seed_ref, out_ref):
    from jax.experimental.pallas import tpu as pltpu
    pltpu.prng_seed(seed_ref[0])
    # logical (unsigned) shift keeps the top bit from smearing; Mosaic
    # can't cast uint32->f32, so bitcast back to int32 (top 8 bits are
    # zero after the shift, value is non-negative) before the cast
    bits = pltpu.bitcast(pltpu.prng_random_bits(out_ref.shape), jnp.uint32)
    small = pltpu.bitcast(bits >> 8, jnp.int32)
    # 24 mantissa-safe bits -> [0, 1)
    out_ref[...] = small.astype(jnp.float32) * (1.0 / (1 << 24))


@functools.partial(jax.jit, static_argnames=("shape",))
def pallas_uniform(seed, shape):
    """Uniform [0,1) floats from the TPU hardware PRNG."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return pl.pallas_call(
        _pallas_uniform_kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
    )(jnp.asarray([seed], jnp.int32))


pallas_uniform = _track_jit("ops.pallas_uniform", pallas_uniform)


def uniform(key_or_seed, shape, use_pallas=None):
    """Uniform [0,1) tensor.  Picks the Pallas hardware-PRNG path on TPU,
    threefry elsewhere (both deterministic in their seed)."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas and isinstance(key_or_seed, int):
        # the hardware PRNG seed register is 32-bit
        return pallas_uniform(key_or_seed & 0x7FFFFFFF, shape)
    key = (jax.random.key(key_or_seed)
           if isinstance(key_or_seed, int) else key_or_seed)
    return jax.random.uniform(key, shape)


class Uniform(AcceleratedUnit):
    """Unit filling ``output`` with fresh uniforms each run
    (ref: veles/prng/uniform.py:49) — dropout masks etc.

    Randomness is *data*: the per-run key is an input to the traced step,
    so the fused program stays pure and reproducible.
    """

    READS = ("key",)
    WRITES = ("output",)
    # run() mutates the key Array host-side before stepping; inside a
    # fused segment that refresh would land after the segment executed
    FUSABLE = False

    def __init__(self, workflow, output_shape=None, prng_key="default",
                 **kwargs):
        super(Uniform, self).__init__(workflow, **kwargs)
        self.output_shape = tuple(output_shape or ())
        self.prng_name = prng_key
        self.output = Array()
        self.key = Array()

    def initialize(self, device=None, **kwargs):
        self.output.reset(numpy.zeros(self.output_shape, numpy.float32))
        gen = prng.get(self.prng_name)
        # key width depends on the active jax PRNG impl (threefry=2 words,
        # rbg=4) — size the buffer from an actual key
        key_shape = numpy.asarray(
            jax.random.key_data(gen.peek_key())).shape
        self.key.reset(numpy.zeros(key_shape, numpy.uint32))
        self._refresh_key(gen)
        super(Uniform, self).initialize(device=device, **kwargs)

    def _refresh_key(self, gen=None):
        gen = gen or prng.get(self.prng_name)
        raw = jax.random.key_data(gen.key())
        self.key.map_invalidate()
        self.key.mem[...] = numpy.asarray(raw)
        self.key.unmap()

    def run(self):
        self._refresh_key()
        super(Uniform, self).run()

    def step(self, key):
        k = jax.random.wrap_key_data(key.astype(jnp.uint32))
        return {"output": jax.random.uniform(k, self.output_shape)}
