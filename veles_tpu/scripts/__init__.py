"""scripts — operational command-line tools (rebuild of
veles/scripts/): compare_snapshots (parameter diffing).  The
reference's bboxer image-labeling web tool and frontend generator are
web assets outside this rebuild's scope; forge maintenance lives in
``python -m veles_tpu.forge``."""
