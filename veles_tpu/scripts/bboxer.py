"""bboxer — browser bounding-box labeling tool (rebuild of
veles/scripts/bboxer.py: the reference served an image tree with a
canvas UI and stored box selections server-side).

Stdlib-only web app: walks ``--root`` for images, serves a one-page
canvas editor (click-drag to draw, double-click a box to delete,
arrow keys / buttons to move between images, label text box), and
persists every change to ``--out`` (default ``bboxes.json`` in the
root) as ``{relative/path: [{"x","y","w","h","label"}]}`` — a format
an image loader can consume directly.

Usage: ``python -m veles_tpu.scripts.bboxer --root DIR [--port N]``
"""

import argparse
import json
import os
import sys
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")

_PAGE = """<!DOCTYPE html>
<html><head><title>bboxer</title><style>
 body { font-family: sans-serif; margin: 1em; }
 #wrap { position: relative; display: inline-block; }
 #img { display: block; max-width: 90vw; max-height: 80vh; }
 #overlay { position: absolute; left: 0; top: 0; cursor: crosshair; }
 .bar { margin: .5em 0; }
 button { margin-right: .5em; }
</style></head><body>
<div class="bar">
 <button id="prev">&#8592; prev</button>
 <button id="next">next &#8594;</button>
 label <input id="label" value="object" size="12">
 <span id="status"></span>
</div>
<div id="wrap"><img id="img"><canvas id="overlay"></canvas></div>
<script>
let images = [], idx = 0, boxes = [], drag = null;
const img = document.getElementById('img'),
      cv = document.getElementById('overlay'),
      ctx = cv.getContext('2d');
function redraw() {
  ctx.clearRect(0, 0, cv.width, cv.height);
  ctx.lineWidth = 2; ctx.strokeStyle = '#e33'; ctx.fillStyle = '#e33';
  ctx.font = '13px sans-serif';
  for (const b of boxes) {
    ctx.strokeRect(b.x * cv.width, b.y * cv.height,
                   b.w * cv.width, b.h * cv.height);
    ctx.fillText(b.label, b.x * cv.width + 3, b.y * cv.height + 14);
  }
  if (drag) ctx.strokeRect(drag.x0, drag.y0,
                           drag.x1 - drag.x0, drag.y1 - drag.y0);
  document.getElementById('status').textContent =
    (images[idx] || '?') + '  (' + (idx + 1) + '/' + images.length +
    ', ' + boxes.length + ' box(es))';
}
async function save() {
  await fetch('/api/boxes?' + new URLSearchParams({path: images[idx]}),
              {method: 'POST', body: JSON.stringify(boxes)});
}
async function show(i) {
  idx = (i + images.length) % images.length;
  img.src = '/image/' + images[idx];
  await img.decode().catch(() => {});
  cv.width = img.clientWidth; cv.height = img.clientHeight;
  boxes = await (await fetch('/api/boxes?' +
    new URLSearchParams({path: images[idx]}))).json();
  redraw();
}
cv.addEventListener('mousedown', e => {
  drag = {x0: e.offsetX, y0: e.offsetY, x1: e.offsetX, y1: e.offsetY};
});
cv.addEventListener('mousemove', e => {
  if (drag) { drag.x1 = e.offsetX; drag.y1 = e.offsetY; redraw(); }
});
cv.addEventListener('mouseup', async e => {
  if (!drag) return;
  const x = Math.min(drag.x0, drag.x1) / cv.width,
        y = Math.min(drag.y0, drag.y1) / cv.height,
        w = Math.abs(drag.x1 - drag.x0) / cv.width,
        h = Math.abs(drag.y1 - drag.y0) / cv.height;
  drag = null;
  if (w > 0.005 && h > 0.005)
    boxes.push({x, y, w, h,
                label: document.getElementById('label').value});
  redraw(); await save();
});
cv.addEventListener('dblclick', async e => {
  const px = e.offsetX / cv.width, py = e.offsetY / cv.height;
  boxes = boxes.filter(b => !(px >= b.x && px <= b.x + b.w &&
                              py >= b.y && py <= b.y + b.h));
  redraw(); await save();
});
document.getElementById('prev').onclick = () => show(idx - 1);
document.getElementById('next').onclick = () => show(idx + 1);
document.addEventListener('keydown', e => {
  if (e.key === 'ArrowLeft') show(idx - 1);
  if (e.key === 'ArrowRight') show(idx + 1);
});
fetch('/api/images').then(r => r.json()).then(l => {
  images = l; if (images.length) show(0); else redraw();
});
</script></body></html>
"""


class BBoxStore:
    """Selections file: {relative image path: [box dicts]}."""

    def __init__(self, path):
        self.path = path
        self._lock = threading.Lock()
        self.data = {}
        if os.path.isfile(path):
            with open(path) as f:
                self.data = json.load(f)

    def get(self, image):
        return self.data.get(image, [])

    def put(self, image, boxes):
        with self._lock:
            if boxes:
                self.data[image] = boxes
            else:
                self.data.pop(image, None)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.data, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)


def scan_images(root):
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if fn.lower().endswith(IMAGE_EXTENSIONS):
                out.append(os.path.relpath(os.path.join(dirpath, fn),
                                           root))
    return sorted(out)


def make_server(root, store, host="127.0.0.1", port=0):
    images = scan_images(root)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, obj, code=200):
            blob = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def _safe_rel(self, rel):
            rel = urllib.parse.unquote(rel)
            full = os.path.realpath(os.path.join(root, rel))
            if not full.startswith(os.path.realpath(root) + os.sep):
                return None, None  # path escape attempt
            return rel, full

        def do_GET(self):
            url = urllib.parse.urlparse(self.path)
            if url.path == "/":
                blob = _PAGE.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)
            elif url.path == "/api/images":
                self._json(images)
            elif url.path == "/api/boxes":
                q = dict(urllib.parse.parse_qsl(url.query))
                rel, _ = self._safe_rel(q.get("path", ""))
                self._json(store.get(rel) if rel else [])
            elif url.path.startswith("/image/"):
                rel, full = self._safe_rel(url.path[len("/image/"):])
                if not rel or not os.path.isfile(full):
                    self.send_error(404)
                    return
                with open(full, "rb") as f:
                    blob = f.read()
                self.send_response(200)
                self.send_header("Content-Type", "image/*")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)
            else:
                self.send_error(404)

        def do_POST(self):
            url = urllib.parse.urlparse(self.path)
            if url.path != "/api/boxes":
                self.send_error(404)
                return
            q = dict(urllib.parse.parse_qsl(url.query))
            rel, _ = self._safe_rel(q.get("path", ""))
            if rel is None:
                self.send_error(400)
                return
            length = int(self.headers.get("Content-Length", 0))
            boxes = json.loads(self.rfile.read(length) or b"[]")
            store.put(rel, boxes)
            self._json({"ok": True, "count": len(boxes)})

    return ThreadingHTTPServer((host, port), Handler)


def main(argv=None):
    p = argparse.ArgumentParser(prog="veles_tpu.scripts.bboxer")
    p.add_argument("--root", required=True, help="image tree")
    p.add_argument("--out", help="selections file "
                   "(default: <root>/bboxes.json)")
    p.add_argument("--port", type=int, default=8094)
    p.add_argument("--host", default="127.0.0.1")
    args = p.parse_args(argv)
    store = BBoxStore(args.out or os.path.join(args.root, "bboxes.json"))
    server = make_server(args.root, store, args.host, args.port)
    print("bboxer on http://%s:%d/ (%d images)"
          % (args.host, server.server_address[1],
             len(scan_images(args.root))))
    server.serve_forever()
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
