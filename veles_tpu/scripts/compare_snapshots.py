"""compare_snapshots — parameter diff between two workflow snapshots
(rebuild of veles/scripts/compare_snapshots.py).

Usage: ``python -m veles_tpu.scripts.compare_snapshots a.pickle.gz
b.pickle.gz``  — prints per-parameter L2/Linf deltas and a summary
verdict (identical / close / diverged)."""

import argparse
import sys

import numpy


def snapshot_params(path):
    """{layer_name/param: numpy array} of a snapshot's forward chain."""
    from veles_tpu.snapshotter import SnapshotterToFile
    wf = SnapshotterToFile.import_file(path)
    forwards = getattr(wf, "forwards", None)
    if not forwards:
        raise ValueError("%s has no forward chain" % path)
    out = {}
    for u in forwards:
        for name, arr in u.param_arrays().items():
            out["%s/%s" % (u.name, name)] = numpy.asarray(
                arr.map_read().mem)
    return out


def compare(params_a, params_b):
    rows = []
    for key in sorted(set(params_a) | set(params_b)):
        a = params_a.get(key)
        b = params_b.get(key)
        if a is None or b is None:
            rows.append((key, None, None, "only in %s"
                         % ("B" if a is None else "A")))
            continue
        if a.shape != b.shape:
            rows.append((key, None, None,
                         "shape %s vs %s" % (a.shape, b.shape)))
            continue
        diff = a.astype(numpy.float64) - b
        rows.append((key, float(numpy.sqrt((diff ** 2).mean())),
                     float(numpy.abs(diff).max()), ""))
    return rows


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="veles_tpu.scripts.compare_snapshots")
    p.add_argument("snapshot_a")
    p.add_argument("snapshot_b")
    p.add_argument("--atol", type=float, default=1e-6,
                   help="max |delta| treated as identical")
    args = p.parse_args(argv)
    rows = compare(snapshot_params(args.snapshot_a),
                   snapshot_params(args.snapshot_b))
    worst = 0.0
    print("%-32s %12s %12s" % ("parameter", "rmse", "max|delta|"))
    for key, rmse, linf, note in rows:
        if note:
            print("%-32s %s" % (key, note))
            worst = float("inf")
        else:
            print("%-32s %12.3e %12.3e" % (key, rmse, linf))
            worst = max(worst, linf)
    if worst <= args.atol:
        print("VERDICT: identical (within %g)" % args.atol)
        return 0
    print("VERDICT: diverged (max delta %.3e)" % worst)
    return 1


if __name__ == "__main__":
    sys.exit(main())
