"""update_forge — push every packaged workflow under a tree to a forge
server (rebuild of veles/scripts/update_forge.py: the reference walked
its sample workflows and uploaded each folder carrying a forge
manifest).

Here the unit of publication is a ``forge.json`` manifest next to a
``package_export`` archive::

    {"name": "mnist-mlp", "version": "1.2",
     "description": "...", "package": "mnist.tar.gz"}

Every manifest found under ``--root`` is uploaded; a version that
already exists on the server is skipped (the store's history is
immutable — HTTP 409).

Usage: ``python -m veles_tpu.scripts.update_forge --server URL
[--root DIR]``  (``FORGE_SERVER`` env is the --server fallback,
like the reference).
"""

import argparse
import json
import logging
import os
import sys
import urllib.error

log = logging.getLogger("update_forge")

MANIFEST = "forge.json"


def find_manifests(root):
    for dirpath, _dirnames, filenames in os.walk(root):
        if MANIFEST in filenames:
            yield os.path.join(dirpath, MANIFEST)


def upload_manifest(server, manifest_path):
    """Upload one manifest's package; returns "uploaded" | "exists" |
    "error"."""
    from veles_tpu.forge.client import upload
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        package = os.path.join(os.path.dirname(manifest_path),
                               manifest["package"])
    except (OSError, json.JSONDecodeError, KeyError, TypeError) as e:
        # one broken manifest must not abort the rest of the sweep
        log.error("%s: unreadable manifest: %s", manifest_path, e)
        return "error"
    if not os.path.isfile(package):
        log.error("%s: package %s missing", manifest_path, package)
        return "error"
    try:
        meta = upload(server, manifest["name"],
                      str(manifest.get("version", "1.0")), package,
                      description=manifest.get("description", ""))
        log.info("uploaded %s==%s (%d bytes)", meta["name"],
                 meta["version"], meta["size"])
        return "uploaded"
    except urllib.error.HTTPError as e:
        if e.code == 409:
            log.info("%s==%s already on the server — skipped",
                     manifest["name"], manifest.get("version", "1.0"))
            return "exists"
        log.error("%s: upload failed: %s", manifest_path, e)
        return "error"
    except Exception as e:
        log.error("%s: upload failed: %s", manifest_path, e)
        return "error"


def main(argv=None):
    p = argparse.ArgumentParser(prog="veles_tpu.scripts.update_forge")
    p.add_argument("--server", default=os.getenv("FORGE_SERVER"),
                   help="forge server URL (or FORGE_SERVER env)")
    p.add_argument("--root", default=".",
                   help="tree to scan for %s manifests" % MANIFEST)
    args = p.parse_args(argv)
    if not args.server:
        p.error("no forge server: pass --server or set FORGE_SERVER")
    statuses = [upload_manifest(args.server, m)
                for m in find_manifests(args.root)]
    if not statuses:
        log.warning("no %s manifests under %s", MANIFEST, args.root)
    return 1 if "error" in statuses else 0


if __name__ == "__main__":  # pragma: no cover
    logging.basicConfig(level=logging.INFO)
    sys.exit(main())
