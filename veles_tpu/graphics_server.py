"""GraphicsServer — ZMQ PUB fan-out of plot payloads.

Rebuild of veles/graphics_server.py:73-135: the training process binds a
PUB socket (tcp + inproc endpoints) and pushes each plotter payload as
one gzip-pickled message; any number of client processes subscribe.
The reference additionally offered epgm multicast — out of scope on a
TPU pod's DCN, where the web-status tier covers fan-out.
"""

import gzip
import pickle

from veles_tpu.logger import Logger

try:
    import zmq
    HAS_ZMQ = True
except ImportError:  # pragma: no cover
    HAS_ZMQ = False


class GraphicsServer(Logger):
    """PUB endpoint for plot payloads (ref: graphics_server.py:73)."""

    def __init__(self, port=0, host="127.0.0.1"):
        super(GraphicsServer, self).__init__()
        if not HAS_ZMQ:  # pragma: no cover
            raise RuntimeError("pyzmq is unavailable")
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PUB)
        self._sock.setsockopt(zmq.LINGER, 0)
        if port:
            self._sock.bind("tcp://%s:%d" % (host, port))
            self.port = port
        else:
            self.port = self._sock.bind_to_random_port("tcp://" + host)
        self.endpoint = "tcp://%s:%d" % (host, self.port)
        self.sent = 0
        self.info("graphics PUB on %s", self.endpoint)

    def enqueue(self, payload):
        """Publish one plot payload (non-blocking; slow subscribers drop
        — live plots must never stall training)."""
        blob = gzip.compress(
            pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL), 1)
        try:
            self._sock.send(blob, zmq.NOBLOCK)
            self.sent += 1
        except zmq.ZMQError:  # pragma: no cover - full HWM
            pass

    def close(self):
        self._sock.close(0)
