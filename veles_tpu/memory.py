"""Array — the host/device data pair (rebuild of veles/memory.py).

The reference's ``Array`` kept a numpy host mirror plus an OpenCL/CUDA
buffer with an explicit ``map_read / map_write / map_invalidate / unmap``
coherence protocol (ref: veles/memory.py:110-511).  On TPU the same object
exists at the *boundary* of jitted programs: loaders fill the host mirror,
``unmap()`` materialises a ``jax.Array`` in HBM, jitted workflow segments
consume and produce jax.Arrays, and ``map_read()`` brings results back for
plotting / snapshotting / metrics.  Inside a jitted segment there is no
map/unmap — XLA owns the buffers — so the protocol's cost disappears from
the hot path by design rather than by discipline.

Coherence is a 3-state machine instead of the reference's mapping
counters:

- ``HOST_DIRTY``  — host mirror newer (after map_write/map_invalidate);
- ``DEV_DIRTY``   — device buffer newer (after a jitted step wrote it);
- ``COHERENT``    — both views agree.

``Watcher`` keeps the global byte accounting the reference printed at
exit (ref: veles/memory.py:56-107, veles/__main__.py:779-797).
"""

import threading

import jax
import numpy

from veles_tpu.distributable import Pickleable

COHERENT = 0
HOST_DIRTY = 1
DEV_DIRTY = 2


class Watcher:
    """Global device-memory byte accounting
    (ref: veles/memory.py:56-107)."""

    _lock = threading.Lock()
    #: device repr -> bytes currently resident
    used = {}
    peak = 0

    @classmethod
    def alloc(cls, device, nbytes):
        with cls._lock:
            key = str(device)
            cls.used[key] = cls.used.get(key, 0) + nbytes
            cls.peak = max(cls.peak, sum(cls.used.values()))

    @classmethod
    def free(cls, device, nbytes):
        with cls._lock:
            key = str(device)
            cls.used[key] = max(0, cls.used.get(key, 0) - nbytes)

    @classmethod
    def total(cls):
        with cls._lock:
            return sum(cls.used.values())

    @classmethod
    def report(cls):
        with cls._lock:
            return dict(cls.used), cls.peak

    @classmethod
    def reset(cls):
        with cls._lock:
            cls.used.clear()
            cls.peak = 0


class Array(Pickleable):
    """Host numpy mirror + device jax.Array (ref: veles/memory.py:110).

    Usage::

        a = Array(numpy.zeros((128, 784), numpy.float32))
        a.initialize(device)          # allocate / upload
        a.map_write(); a.mem[...] = batch; a.unmap()   # host -> HBM
        out = jitted_fn(a.devmem)                       # device compute
        a.devmem = out                                  # adopt result
        a.map_read(); print(a.mem.mean())               # HBM -> host
    """

    def __init__(self, data=None, shape=None, dtype=numpy.float32):
        super(Array, self).__init__()
        if data is not None:
            self._mem = numpy.ascontiguousarray(data)
        elif shape is not None:
            self._mem = numpy.zeros(shape, dtype=dtype)
        else:
            self._mem = None
        self._state = HOST_DIRTY if self._mem is not None else COHERENT

    def init_unpickled(self):
        super(Array, self).init_unpickled()
        self._devmem_ = None
        self._device_ = None
        # snapshots store only the host mirror; device side is re-created
        # by the next initialize() (ref: veles/memory.py:284-292)
        if getattr(self, "_mem", None) is not None:
            self._state = HOST_DIRTY

    # -- host side -----------------------------------------------------------

    @property
    def mem(self):
        """The host numpy mirror.  Call :meth:`map_read`/:meth:`map_write`
        first when a device buffer exists."""
        return self._mem

    @mem.setter
    def mem(self, value):
        self._mem = numpy.ascontiguousarray(value) \
            if value is not None else None
        self._state = HOST_DIRTY

    def reset(self, data=None):
        """Drop both views and optionally adopt new host data
        (ref: veles/memory.py:330)."""
        self._release_devmem()
        self._mem = None if data is None else numpy.ascontiguousarray(data)
        self._state = HOST_DIRTY if data is not None else COHERENT

    # -- device side ---------------------------------------------------------

    @property
    def devmem(self):
        """The device jax.Array (uploads lazily if the host is newer)."""
        if self._state == HOST_DIRTY or self._devmem_ is None:
            self._upload()
        return self._devmem_

    def _aliases_host(self, devmem):
        """True when the host mirror and ``devmem`` share one
        allocation.  XLA:CPU makes this common in BOTH directions:
        ``jax.device_put`` borrows small (≲16 KB) numpy buffers
        zero-copy, and ``numpy.asarray(devmem)`` (map_read) returns a
        view of the device buffer.  Unknown layouts (sharded arrays
        without a host pointer) report True — the safe answer."""
        if self._mem is None or devmem is None:
            return False
        try:
            return devmem.unsafe_buffer_pointer() \
                == self._mem.ctypes.data
        except Exception:
            # no single host pointer (sharded array): only the CPU
            # backend can alias host memory at all, so assume the
            # worst there and nothing elsewhere
            try:
                plat = next(iter(devmem.devices())).platform
            except Exception:
                return True
            return plat == "cpu"

    def donatable_devmem(self):
        """The device buffer, guaranteed safe to DONATE
        (``donate_argnums``).  When host mirror and device buffer
        share an allocation, donation lets XLA reuse — and write its
        own (differently padded) output layout over — memory the host
        side still references or OWNS: glibc's "corrupted size vs.
        prev_size" family, the documented span-step heap corruption
        (ROUND6_NOTES.md).  Detaches with ONE device-side copy, paid
        only on the first step after a host write (init, snapshot
        resume, DCN master/slave apply) — steady-state steps adopt
        pure device outputs (DEV_DIRTY) and return the buffer as-is."""
        dm = self.devmem
        if self._state != COHERENT or not self._aliases_host(dm):
            return dm
        import jax.numpy as jnp
        fresh = jnp.copy(dm)   # device-owned, never host-aliased
        self._release_devmem()
        self._devmem_ = fresh
        Watcher.alloc(self._watch_key(), fresh.nbytes)
        return fresh

    @devmem.setter
    def devmem(self, value):
        """Adopt a jitted-program output as the new device buffer."""
        self._release_devmem()
        self._devmem_ = value
        if value is not None:
            Watcher.alloc(self._watch_key(), value.nbytes)
            self._state = DEV_DIRTY

    def adopt(self, mem, devmem=None, dev_dirty=False):
        """Install a prepared (host mirror, device buffer) pair
        WITHOUT copying or invalidating — the buffer-pool handoff of
        the asynchronous input pipeline (loader/prefetch.py).  Unlike
        the ``mem`` setter (which marks HOST_DIRTY and forces a
        re-upload on the next :attr:`devmem` read), both views are
        taken as already in agreement: consumers get the prefetched
        device handle with no host↔device traffic on the hot path.
        ``dev_dirty=True`` records that only the device side is live
        (a device-gather fill) so :meth:`map_read` still fetches."""
        self._release_devmem()
        self._mem = mem
        self._devmem_ = devmem
        if devmem is not None:
            Watcher.alloc(self._watch_key(), devmem.nbytes)
            self._state = DEV_DIRTY if dev_dirty else COHERENT
        else:
            self._state = HOST_DIRTY

    def _watch_key(self):
        if self._devmem_ is not None:
            try:
                return next(iter(self._devmem_.devices()))
            except Exception:
                pass
        return self._device_.jax_device if self._device_ else "host"

    def _release_devmem(self):
        if self._devmem_ is not None:
            Watcher.free(self._watch_key(), self._devmem_.nbytes)
            self._devmem_ = None

    def _upload(self):
        if self._mem is None:
            return
        self._release_devmem()
        dev = self._device_.jax_device if self._device_ is not None else None
        if dev is not None:
            self._devmem_ = jax.device_put(self._mem, dev)
        else:
            self._devmem_ = jax.device_put(self._mem)
        Watcher.alloc(self._watch_key(), self._devmem_.nbytes)
        self._state = COHERENT

    def initialize(self, device=None):
        """Bind to a Device (ref: veles/memory.py:347).  The device
        buffer materialises lazily on first :attr:`devmem` access — an
        eager upload here would push every freshly-reset zero buffer
        (layer outputs, minibatch staging) over the host↔HBM link even
        when the fused/span programs never read them."""
        if device is not None:
            if self._devmem_ is not None and self._state != HOST_DIRTY:
                # migrate only if the live buffer is on a DIFFERENT jax
                # device — adopted program outputs (e.g. solver slots
                # born on-device) must not round-trip through the host
                # just because their Array wasn't device-bound yet
                try:
                    cur = next(iter(self._devmem_.devices()))
                except Exception:
                    cur = None
                if cur is not None and cur != device.jax_device:
                    self.map_read()
                    self._release_devmem()
            self._device_ = device
        return self

    # -- coherence protocol (ref: veles/memory.py:371-384) -------------------

    def map_read(self):
        """Make the host mirror current."""
        if self._state == DEV_DIRTY and self._devmem_ is not None:
            self._mem = self._fetch_host(self._devmem_)
            self._state = COHERENT
        return self

    @staticmethod
    def _fetch_host(devmem):
        """Device→host fetch that also works for multi-host arrays:
        fully-replicated global arrays read the local shard.  A
        cross-process *sharded* array is refused — the implicit
        allgather would be a blocking collective inside a host-side
        read, deadlocking any process-divergent code path; callers that
        really want it use multihost.process_allgather explicitly."""
        try:
            return numpy.asarray(devmem)
        except RuntimeError:
            sharding = devmem.sharding
            if getattr(sharding, "is_fully_replicated", False):
                shard = next(iter(devmem.addressable_shards))
                return numpy.asarray(shard.data)
            raise RuntimeError(
                "host read of a cross-process sharded array — gather it "
                "explicitly with veles_tpu.parallel.multihost."
                "process_allgather (an implicit collective here could "
                "deadlock the gang)")

    def map_write(self):
        """Host mirror current *and* about to be written."""
        self.map_read()
        if self._mem is not None and (
                not self._mem.flags.writeable
                or (self._state == COHERENT
                    and self._aliases_host(self._devmem_))):
            # map_read may have adopted a read-only view of the device
            # buffer — writers need their own copy; a WRITEABLE mirror
            # can still share the device buffer's allocation (zero-copy
            # device_put of a small host array), and writing through it
            # would mutate a buffer an asynchronously-dispatched XLA
            # program may still be reading
            self._mem = numpy.array(self._mem)
        self._state = HOST_DIRTY
        return self

    def map_invalidate(self):
        """Host will be fully overwritten — skip the device→host copy."""
        if self._mem is None and self._devmem_ is not None:
            self._mem = numpy.zeros(self._devmem_.shape, self._devmem_.dtype)
        elif self._mem is not None and (
                not self._mem.flags.writeable
                or (self._state == COHERENT
                    and self._aliases_host(self._devmem_))):
            self._mem = numpy.array(self._mem)
        self._state = HOST_DIRTY
        return self

    def unmap(self):
        """Flush host writes to the device buffer."""
        if self._state == HOST_DIRTY:
            self._upload()
        return self

    def __getstate__(self):
        # snapshot must capture the freshest view: a DEV_DIRTY buffer is
        # pulled back to the host first (ref: veles/memory.py:284-292)
        self.map_read()
        return super(Array, self).__getstate__()

    # -- conveniences --------------------------------------------------------

    @property
    def shape(self):
        if self._mem is not None:
            return self._mem.shape
        if self._devmem_ is not None:
            return self._devmem_.shape
        return None

    @property
    def dtype(self):
        if self._mem is not None:
            return self._mem.dtype
        if self._devmem_ is not None:
            return numpy.dtype(self._devmem_.dtype)
        return None

    @property
    def size(self):
        s = self.shape
        return int(numpy.prod(s)) if s is not None else 0

    @property
    def nbytes(self):
        return self.size * (self.dtype.itemsize if self.dtype else 0)

    def __bool__(self):
        return self._mem is not None or self._devmem_ is not None

    def __len__(self):
        s = self.shape
        return s[0] if s else 0

    def __getitem__(self, idx):
        self.map_read()
        return self._mem[idx]

    def __setitem__(self, idx, value):
        self.map_write()
        self._mem[idx] = value

    def __array__(self, dtype=None):
        self.map_read()
        return self._mem if dtype is None else self._mem.astype(dtype)

    def __repr__(self):
        return "<Array shape=%s dtype=%s state=%s>" % (
            self.shape, self.dtype,
            {COHERENT: "coherent", HOST_DIRTY: "host-dirty",
             DEV_DIRTY: "dev-dirty"}[self._state])


def roundup(num, align):
    """Round ``num`` up to a multiple of ``align``
    (ref: veles/numpy_ext.py roundup) — used for batch padding so shapes
    stay static under jit."""
    rem = num % align
    return num if rem == 0 else num + (align - rem)
