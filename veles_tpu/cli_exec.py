"""Shared subprocess-evaluation harness.

Genetics individuals and ensemble instances are both evaluated by
re-running the CLI with ``--result-file`` (ref:
veles/ensemble/base_workflow.py:135-152 — genetics shells out the same
way); this is the one copy of that contract.
"""

import json
import logging
import os
import subprocess
import tempfile

log = logging.getLogger("cli_exec")


def run_cli_collect_results(argv, timeout=None):
    """Run ``argv + [--result-file tmp]``; return the parsed metrics
    dict, or None on any failure (logged, never raised — a dead
    individual/instance must not kill the fleet)."""
    with tempfile.NamedTemporaryFile(
            mode="r", suffix=".json", delete=False) as f:
        result_file = f.name
    argv = list(argv) + ["--result-file", result_file]
    try:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout, cwd=os.getcwd())
        if proc.returncode != 0:
            log.warning("subprocess failed (rc=%d): %s", proc.returncode,
                        proc.stderr[-500:])
            return None
        with open(result_file) as f:
            return json.load(f)
    except (subprocess.TimeoutExpired, OSError, ValueError) as e:
        log.warning("subprocess evaluation error: %s", e)
        return None
    finally:
        try:
            os.unlink(result_file)
        except OSError:
            pass
