"""Import a workflow file as a module (rebuild of veles/import_file.py)."""

import importlib.util
import os
import sys


def import_file_as_module(path, name=None):
    path = os.path.abspath(path)
    name = name or os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    # registered so pickling classes defined in the workflow file works
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module
