"""Avatar — cross-workflow Array bridging (rebuild of
veles/avatar.py:22).

One workflow exposes chosen Arrays through an :class:`AvatarServer`
(ZMQ REP); an :class:`Avatar` unit in another process/workflow pulls
fresh copies each run.  The reference used the same shape to let a
secondary workflow observe a primary's tensors without sharing memory.
"""

import pickle

from veles_tpu.safe_pickle import safe_loads

from veles_tpu.logger import Logger
from veles_tpu.memory import Array
from veles_tpu.units import Unit

try:
    import zmq
    HAS_ZMQ = True
except ImportError:  # pragma: no cover
    HAS_ZMQ = False


class AvatarServer(Logger):
    """REP endpoint serving {name: Array} snapshots on demand."""

    def __init__(self, arrays, port=0, host="127.0.0.1"):
        super(AvatarServer, self).__init__()
        if not HAS_ZMQ:  # pragma: no cover
            raise RuntimeError("pyzmq is unavailable")
        self.arrays = dict(arrays)
        self._sock = zmq.Context.instance().socket(zmq.REP)
        if port:
            self._sock.bind("tcp://%s:%d" % (host, port))
            self.port = port
        else:
            self.port = self._sock.bind_to_random_port("tcp://" + host)
        self.endpoint = "tcp://%s:%d" % (host, self.port)
        self.info("avatar server on %s", self.endpoint)
        from veles_tpu.safe_pickle import warn_if_public
        warn_if_public(self.endpoint, self)

    def serve_once(self, timeout=5000):
        """Answer one request; returns False on timeout."""
        if not self._sock.poll(timeout):
            return False
        names = safe_loads(self._sock.recv())
        payload = {}
        for name in names or self.arrays:
            arr = self.arrays.get(name)
            if isinstance(arr, Array):
                payload[name] = arr.map_read().mem
        self._sock.send(pickle.dumps(
            payload, protocol=pickle.HIGHEST_PROTOCOL))
        return True

    def close(self):
        self._sock.close(0)


class Avatar(Unit):
    """Pulls remote Arrays into local mirrors each run
    (ref: veles/avatar.py:22)."""

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, endpoint=None, names=(), timeout=5.0,
                 **kwargs):
        super(Avatar, self).__init__(workflow, **kwargs)
        self.endpoint = endpoint
        self.names = list(names)
        self.timeout = timeout
        #: name -> local Array mirror, created on first fetch
        self.mirrors = {}
        self.demand("endpoint")

    def init_unpickled(self):
        super(Avatar, self).init_unpickled()
        self._sock_ = None

    def _connect(self):
        if not HAS_ZMQ:  # pragma: no cover
            raise RuntimeError("pyzmq is unavailable")
        if self._sock_ is None:
            self._sock_ = zmq.Context.instance().socket(zmq.REQ)
            self._sock_.connect(self.endpoint)

    def run(self):
        self._connect()
        self._sock_.send(pickle.dumps(self.names or None))
        if not self._sock_.poll(self.timeout * 1000):
            raise TimeoutError("avatar source %s silent" % self.endpoint)
        payload = safe_loads(self._sock_.recv())
        for name, mem in payload.items():
            mirror = self.mirrors.get(name)
            if mirror is None:
                mirror = self.mirrors[name] = Array()
            mirror.reset(mem)
