"""Slot-based batched KV cache.

One fixed ``[max_slots, window, d]`` K/V buffer pair per cacheable
block, shared by every in-flight request: request ↔ slot row.  A slot
row's lifecycle:

- **alloc** — a request leaves the queue and claims a free slot;
- **insert** — its batched prefill row (window-width, rows past the
  prompt zeroed) REPLACES the slot row wholesale, so stale K/V from
  the previous occupant can never leak into the newcomer's attention;
- **decode** — the shared compiled step (:mod:`serving.engine`)
  writes position ``len-1`` and attends over ``[0, len)`` per slot;
- **release** — stop-token / step-limit frees the row for the next
  request (no zeroing needed: insert overwrites).

All methods must be called from ONE thread (the scheduler's decode
loop) — the arrays are plain jax values, swapped functionally.
"""

import jax
import jax.numpy as jnp


@jax.jit
def _insert_row(dst, src, slot):
    # slot rides traced so every insert shares one executable
    return jax.lax.dynamic_update_slice(
        dst, src.astype(dst.dtype), (slot, jnp.int32(0), jnp.int32(0)))


class SlotKVCache:
    """Per-layer slot-major K/V buffers + free-slot bookkeeping."""

    def __init__(self, forwards, max_slots, window):
        from veles_tpu import dtypes
        self.max_slots = int(max_slots)
        self.window = int(window)
        if self.max_slots < 1 or self.window < 2:
            raise ValueError("need max_slots >= 1 and window >= 2")
        self.caches = {
            i: u.init_cache(self.max_slots, self.window,
                            dtypes.compute_dtype())
            for i, u in enumerate(forwards)
            if hasattr(u, "init_cache")}
        if not self.caches:
            raise ValueError("chain has no cacheable blocks")
        # lowest slot first — keeps occupancy dense and debuggable
        self._free = list(range(self.max_slots - 1, -1, -1))

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def active_slots(self):
        return self.max_slots - len(self._free)

    def alloc(self):
        """Claim a free slot index, or None when all are busy."""
        return self._free.pop() if self._free else None

    def release(self, slot):
        self._free.append(int(slot))

    def insert(self, slot, row_caches):
        """Adopt a prefilled batch-1, window-width cache row
        (:func:`serving.prefill.prefill` output) into ``slot`` —
        replaces the whole row, clearing any previous occupant."""
        s = jnp.int32(slot)
        for i, layer in self.caches.items():
            self.caches[i] = {
                name: _insert_row(layer[name], row_caches[i][name], s)
                for name in layer}
