"""Serving KV caches: block-paged (default) and dense slot rows.

:class:`PagedKVCache` — vLLM-lineage PagedAttention layout (Kwon et
al., SOSP 2023): K/V live in per-layer POOLS of fixed-size blocks
(``[num_blocks, block_size, d]``) plus a per-slot *block table*, so a
request holds ``ceil((prompt + steps) / block_size)`` blocks instead
of a full ``window`` row.  Admission capacity becomes
memory-proportional — short requests pack many more concurrent
streams into the same HBM — and the pool size (``kv_blocks``) is a
knob independent of ``max_slots``.  Physical block 0 is the reserved
TRASH block: never allocated, it absorbs the writes of occupancy-
bucket padding rows and backs the stale tail entries of every table
(see ops/paged_attention.py for why the garbage is exactly masked).

:class:`SlotKVCache` — the legacy dense layout (one fixed
``[max_slots, window, d]`` buffer pair per cacheable block, request ↔
slot row), kept as the parity baseline and the fallback for chains
without a paged step.

A slot's lifecycle in either cache: **alloc** (a request leaves the
queue and claims a slot — and, paged, its whole block budget, so
decode can never die of mid-flight block starvation), **insert** (the
prefilled batch-1 staging row is copied in — block-scattered or
row-replaced), **decode** (the shared compiled step writes position
``len-1`` and attends over ``[0, len)``), **release** (stop-token /
step-limit frees slot + blocks; no zeroing needed — every attended
row [0, len) was written by the current occupant).

All methods must be called from ONE thread (the scheduler's decode
loop) — the arrays are plain jax values, swapped functionally.
"""

import numpy

import jax
import jax.numpy as jnp

from veles_tpu.telemetry import track_jit


def _row_pair(dst_k, dst_v, src_k, src_v, slot):
    # ONE dispatch per layer for the K/V pair (the per-tensor-name
    # variant paid two); slot rides traced so inserts share the
    # executable, src may be narrower than the row (decode rewrites
    # [prompt, len) itself, and rows ≥ len are masked)
    start = (slot, jnp.int32(0), jnp.int32(0))
    return (jax.lax.dynamic_update_slice(
                dst_k, src_k.astype(dst_k.dtype), start),
            jax.lax.dynamic_update_slice(
                dst_v, src_v.astype(dst_v.dtype), start))


_insert_row_pair = track_jit("serving.kv_insert_row",
                             jax.jit(_row_pair))


def _block_pair(pool_k, pool_v, src_k, src_v, ids):
    # batched block copy, K and V in ONE dispatch: src [1, W, d]
    # staging rows -> the table's physical blocks (W and the block
    # count are static through the shapes; one executable per bucket)
    n = ids.shape[0]
    bs = pool_k.shape[1]
    sk = src_k[0, :n * bs].reshape(n, bs, -1)
    sv = src_v[0, :n * bs].reshape(n, bs, -1)
    return (pool_k.at[ids].set(sk.astype(pool_k.dtype)),
            pool_v.at[ids].set(sv.astype(pool_v.dtype)))


_insert_blocks = track_jit("serving.kv_insert_blocks",
                           jax.jit(_block_pair))


def _insert_layer(layer, src, fn, *args):
    """Insert one layer's staging K/V via the paired jitted call,
    falling back per-name for exotic cache pytrees."""
    if set(layer) == {"k", "v"}:
        k, v = fn(layer["k"], layer["v"], src["k"], src["v"], *args)
        return {"k": k, "v": v}
    out = {}
    for name in layer:
        out[name], _ = fn(layer[name], layer[name], src[name],
                          src[name], *args)
    return out


def paged_supported(forwards):
    """True when every cacheable block speaks the paged decode step
    (``apply_step_paged``) — the scheduler otherwise falls back to the
    dense slot cache."""
    has = False
    for u in forwards:
        if hasattr(u, "init_cache"):
            has = True
            if not hasattr(u, "apply_step_paged"):
                return False
    return has


class SlotKVCache:
    """Per-layer dense slot-major K/V buffers + free-slot
    bookkeeping (the legacy layout; parity baseline for the paged
    cache)."""

    def __init__(self, forwards, max_slots, window):
        from veles_tpu import dtypes
        self.max_slots = int(max_slots)
        self.window = int(window)
        if self.max_slots < 1 or self.window < 2:
            raise ValueError("need max_slots >= 1 and window >= 2")
        self.caches = {
            i: u.init_cache(self.max_slots, self.window,
                            dtypes.compute_dtype())
            for i, u in enumerate(forwards)
            if hasattr(u, "init_cache")}
        if not self.caches:
            raise ValueError("chain has no cacheable blocks")
        # lowest slot first — keeps occupancy dense and debuggable
        self._free = list(range(self.max_slots - 1, -1, -1))

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def active_slots(self):
        return self.max_slots - len(self._free)

    def can_admit(self, total_tokens):
        """A dense slot reserves the full window row regardless of
        the request's length — a free slot is the only requirement."""
        return bool(self._free)

    def alloc(self, total_tokens=0):
        """Claim a free slot index, or None when all are busy."""
        return self._free.pop() if self._free else None

    def release(self, slot):
        slot = int(slot)
        if slot in self._free:
            raise ValueError("slot %d double-freed" % slot)
        self._free.append(slot)

    def insert(self, slot, row_caches, length=None):
        """Adopt a prefilled batch-1 staging row (serving/prefill.py
        output, width ≤ window) into ``slot``.  Rows the staging
        didn't cover are stale from the previous occupant — harmless:
        decode attends only over [0, len) and writes every position
        ≥ prompt_len itself, so stale K/V is never read."""
        s = jnp.int32(slot)
        w = self.window
        for i, layer in self.caches.items():
            src = {n: a[:, :w] if a.shape[1] > w else a
                   for n, a in row_caches[i].items()}
            self.caches[i] = _insert_layer(layer, src,
                                           _insert_row_pair, s)


class PagedKVCache:
    """Block-paged K/V pools + per-slot block tables.

    ``block_size`` tokens per block; ``kv_blocks`` — the pool's
    usable capacity in blocks (default: the dense equivalent,
    ``max_slots · ceil(window / block_size)``, so a default-sized pool
    admits everything the dense cache would).  ``window`` stays the
    per-request length bound (the positional-table limit), NOT a
    per-request memory reservation."""

    def __init__(self, forwards, max_slots, window, block_size=16,
                 kv_blocks=None):
        from veles_tpu import dtypes
        self.max_slots = int(max_slots)
        self.window = int(window)
        self.block_size = int(block_size)
        if self.max_slots < 1 or self.window < 2:
            raise ValueError("need max_slots >= 1 and window >= 2")
        if self.block_size < 1:
            raise ValueError("need block_size >= 1")
        self.blocks_per_slot = -(-self.window // self.block_size)
        self.capacity_blocks = int(
            kv_blocks or self.max_slots * self.blocks_per_slot)
        if self.capacity_blocks < 1:
            raise ValueError("need kv_blocks >= 1")
        num = self.capacity_blocks + 1          # + the trash block 0
        self.pools = {
            i: u.init_cache(num, self.block_size,
                            dtypes.compute_dtype())
            for i, u in enumerate(forwards)
            if hasattr(u, "init_cache")}
        if not self.pools:
            raise ValueError("chain has no cacheable blocks")
        self._free_slots = list(range(self.max_slots - 1, -1, -1))
        self._free_blocks = list(range(num - 1, 0, -1))
        #: host-side tables [max_slots, blocks_per_slot]; entries past
        #: a slot's live count stay 0 (the trash block)
        self.tables = numpy.zeros(
            (self.max_slots, self.blocks_per_slot), numpy.int32)
        self.n_blocks = numpy.zeros((self.max_slots,), numpy.int32)

    # -- occupancy reads ------------------------------------------------

    @property
    def free_slots(self):
        return len(self._free_slots)

    @property
    def active_slots(self):
        return self.max_slots - len(self._free_slots)

    @property
    def free_blocks(self):
        return len(self._free_blocks)

    @property
    def used_blocks(self):
        return self.capacity_blocks - len(self._free_blocks)

    def blocks_needed(self, total_tokens):
        return -(-max(int(total_tokens), 1) // self.block_size)

    def can_admit(self, total_tokens):
        """Memory-proportional admission: a free slot AND enough free
        blocks for the request's WHOLE budget (prompt + steps — the
        full reservation up front means decode can never starve for a
        block mid-flight)."""
        return bool(self._free_slots) \
            and self.blocks_needed(total_tokens) <= len(self._free_blocks)

    def alloc(self, total_tokens):
        """Claim a slot and its full block budget, or None when slots
        or blocks are exhausted."""
        need = self.blocks_needed(total_tokens)
        if need > self.blocks_per_slot:
            raise ValueError(
                "request of %d tokens needs %d blocks > %d per-slot "
                "table width" % (total_tokens, need,
                                 self.blocks_per_slot))
        if not self._free_slots or need > len(self._free_blocks):
            return None
        slot = self._free_slots.pop()
        ids = [self._free_blocks.pop() for _ in range(need)]
        self.tables[slot, :need] = ids
        self.tables[slot, need:] = 0
        self.n_blocks[slot] = need
        return slot

    def release(self, slot):
        slot = int(slot)
        if slot in self._free_slots:
            raise ValueError("slot %d double-freed" % slot)
        n = int(self.n_blocks[slot])
        self._free_blocks.extend(int(b) for b in
                                 self.tables[slot, :n][::-1])
        self.tables[slot, :] = 0
        self.n_blocks[slot] = 0
        self._free_slots.append(slot)

    def check(self):
        """Invariant sweep (tests): every block is exactly one of
        {trash, free, owned-by-one-slot}."""
        live = []
        for slot in range(self.max_slots):
            if slot not in self._free_slots:
                live.extend(int(b)
                            for b in self.tables[slot,
                                                 :self.n_blocks[slot]])
        owned = live + [int(b) for b in self._free_blocks]
        assert 0 not in owned, "trash block leaked into circulation"
        assert len(owned) == len(set(owned)), "block double-owned"
        assert len(owned) == self.capacity_blocks, \
            "block leaked: %d tracked of %d" % (len(owned),
                                                self.capacity_blocks)

    def table_rows(self, slots, width):
        """The packed [len(slots), width] block-table batch the
        compiled paged step gathers through."""
        return self.tables[numpy.asarray(slots, numpy.intp), :width]

    def insert(self, slot, row_caches, length):
        """Block-scatter a prefilled batch-1 staging row (width a
        multiple of block_size, rows ≥ length zeroed) into ``slot``'s
        first ``ceil(length / block_size)`` table blocks."""
        need = self.blocks_needed(length)
        if need > int(self.n_blocks[slot]):
            raise ValueError(
                "insert of %d tokens exceeds slot %d's %d-block "
                "budget" % (length, slot, int(self.n_blocks[slot])))
        ids = jnp.asarray(self.tables[slot, :need])
        for i, layer in self.pools.items():
            src = row_caches[i]
            wk = next(iter(src.values())).shape[1]
            if wk < need * self.block_size:
                raise ValueError(
                    "staging width %d < %d blocks x %d" %
                    (wk, need, self.block_size))
            self.pools[i] = _insert_layer(layer, src, _insert_blocks,
                                          ids)
