"""Serving KV caches: block-paged (default) and dense slot rows.

:class:`PagedKVCache` — vLLM-lineage PagedAttention layout (Kwon et
al., SOSP 2023): K/V live in per-layer POOLS of fixed-size blocks
(``[num_blocks, block_size, d]``) plus a per-slot *block table*, so a
request holds ``ceil((prompt + steps) / block_size)`` blocks instead
of a full ``window`` row.  Admission capacity becomes
memory-proportional — short requests pack many more concurrent
streams into the same HBM — and the pool size (``kv_blocks``) is a
knob independent of ``max_slots``.  Physical block 0 is the reserved
TRASH block: never allocated, it absorbs the writes of occupancy-
bucket padding rows and backs the stale tail entries of every table
(see ops/paged_attention.py for why the garbage is exactly masked).

:class:`SlotKVCache` — the legacy dense layout (one fixed
``[max_slots, window, d]`` buffer pair per cacheable block, request ↔
slot row), kept as the parity baseline and the fallback for chains
without a paged step.

A slot's lifecycle in either cache: **alloc** (a request leaves the
queue and claims a slot — and, paged, its whole block budget, so
decode can never die of mid-flight block starvation), **insert** (the
prefilled batch-1 staging row is copied in — block-scattered or
row-replaced), **decode** (the shared compiled step writes position
``len-1`` and attends over ``[0, len)``), **release** (stop-token /
step-limit frees slot + blocks; no zeroing needed — every attended
row [0, len) was written by the current occupant).

All methods must be called from ONE thread (the scheduler's decode
loop) — the arrays are plain jax values, swapped functionally.
"""

import functools

import numpy

import jax
import jax.numpy as jnp

from veles_tpu.telemetry import track_jit


def _row_pair(dst_k, dst_v, src_k, src_v, slot):
    # ONE dispatch per layer for the K/V pair (the per-tensor-name
    # variant paid two); slot rides traced so inserts share the
    # executable, src may be narrower than the row (decode rewrites
    # [prompt, len) itself, and rows ≥ len are masked)
    start = (slot, jnp.int32(0), jnp.int32(0))
    return (jax.lax.dynamic_update_slice(
                dst_k, src_k.astype(dst_k.dtype), start),
            jax.lax.dynamic_update_slice(
                dst_v, src_v.astype(dst_v.dtype), start))


_insert_row_pair = track_jit("serving.kv_insert_row",
                             jax.jit(_row_pair))


def _block_pair(pool_k, pool_v, src_k, src_v, ids, start):
    # batched block copy, K and V in ONE dispatch: src [1, W, d]
    # staging rows [start, start + n·bs) -> the table's physical
    # blocks (W and the block count are static through the shapes;
    # one executable per bucket; start rides traced so warm-prefix
    # inserts — which skip the shared blocks — share it too)
    n = ids.shape[0]
    bs = pool_k.shape[1]
    d = src_k.shape[-1]
    sk = jax.lax.dynamic_slice(
        src_k, (jnp.int32(0), start, jnp.int32(0)),
        (1, n * bs, d))[0].reshape(n, bs, -1)
    sv = jax.lax.dynamic_slice(
        src_v, (jnp.int32(0), start, jnp.int32(0)),
        (1, n * bs, d))[0].reshape(n, bs, -1)
    return (pool_k.at[ids].set(sk.astype(pool_k.dtype)),
            pool_v.at[ids].set(sv.astype(pool_v.dtype)))


_insert_blocks = track_jit("serving.kv_insert_blocks",
                           jax.jit(_block_pair))


@functools.lru_cache(maxsize=1)
def _gather_blocks_jit():
    # built lazily (no module-level executable ref): the prefix-cache
    # warm path copies a matched prefix's pool blocks into a staging
    # row so the cold-tail chunked prefill attends over them — the
    # reverse of _block_pair, K and V in ONE dispatch
    def pair(pool_k, pool_v, dst_k, dst_v, ids):
        n = ids.shape[0]
        bs = pool_k.shape[1]
        sk = pool_k[ids].reshape(1, n * bs, -1)
        sv = pool_v[ids].reshape(1, n * bs, -1)
        return (jax.lax.dynamic_update_slice(
                    dst_k, sk.astype(dst_k.dtype), (0, 0, 0)),
                jax.lax.dynamic_update_slice(
                    dst_v, sv.astype(dst_v.dtype), (0, 0, 0)))
    return track_jit("serving.kv_gather_blocks", jax.jit(pair))


def _quant_block_pair(pool_k, pool_v, scale_k, scale_v, src_k, src_v,
                      ids, start):
    # the int8 counterpart of _block_pair: quantize the staging rows
    # per row on the way in, writing the f32 scales at the SAME
    # [block, row] coordinates — scales follow blocks through every
    # later move (donate / evict / gather) because block ids index
    # both arrays
    from veles_tpu.ops.paged_attention import quantize_kv_rows
    n = ids.shape[0]
    bs = pool_k.shape[1]
    d = src_k.shape[-1]
    sk = jax.lax.dynamic_slice(
        src_k, (jnp.int32(0), start, jnp.int32(0)),
        (1, n * bs, d))[0].reshape(n, bs, -1)
    sv = jax.lax.dynamic_slice(
        src_v, (jnp.int32(0), start, jnp.int32(0)),
        (1, n * bs, d))[0].reshape(n, bs, -1)
    qk, sck = quantize_kv_rows(sk)
    qv, scv = quantize_kv_rows(sv)
    return (pool_k.at[ids].set(qk), pool_v.at[ids].set(qv),
            scale_k.at[ids].set(sck), scale_v.at[ids].set(scv))


@functools.lru_cache(maxsize=1)
def _insert_blocks_q8_jit():
    # lazy like _gather_blocks_jit — no module-level executable ref
    return track_jit("serving.kv_quant_insert_blocks",
                     jax.jit(_quant_block_pair))


@functools.lru_cache(maxsize=1)
def _gather_blocks_q8_jit():
    # warm-path gather out of an INT8 pool: dequantize the resident
    # rows against their scales into the f32 staging row — the cold
    # tail then attends over exactly the K/V later decode steps read
    def pair(pool_k, pool_v, scale_k, scale_v, dst_k, dst_v, ids):
        from veles_tpu.ops.paged_attention import dequantize_kv
        n = ids.shape[0]
        bs = pool_k.shape[1]
        sk = dequantize_kv(pool_k[ids], scale_k[ids],
                           dst_k.dtype).reshape(1, n * bs, -1)
        sv = dequantize_kv(pool_v[ids], scale_v[ids],
                           dst_v.dtype).reshape(1, n * bs, -1)
        return (jax.lax.dynamic_update_slice(dst_k, sk, (0, 0, 0)),
                jax.lax.dynamic_update_slice(dst_v, sv, (0, 0, 0)))
    return track_jit("serving.kv_quant_gather_blocks", jax.jit(pair))


@functools.lru_cache(maxsize=1)
def _export_blocks_jit():
    # disaggregated prefill→decode handoff, the OUT half: gather a
    # slot's blocks RAW out of two same-indexed pool arrays (K/V
    # pair, or the scale pair — the function is dtype/shape generic,
    # so int8 pools and their f32 scales ride the same executable
    # family and the exported bytes are exactly the resident bytes,
    # no dequant round trip)
    def pair(a, b, ids):
        return a[ids], b[ids]
    return track_jit("serving.kv_export_blocks", jax.jit(pair))


@functools.lru_cache(maxsize=1)
def _import_blocks_jit():
    # the IN half: scatter previously exported raw blocks into a
    # decode replica's own table blocks — same generic pairing, so
    # int8 blocks land unrequantized (bit-identical to the exporting
    # pool) and their scales follow through the same call
    def pair(a, b, src_a, src_b, ids):
        return (a.at[ids].set(src_a.astype(a.dtype)),
                b.at[ids].set(src_b.astype(b.dtype)))
    return track_jit("serving.kv_import_blocks", jax.jit(pair))


def _insert_layer(layer, src, fn, *args):
    """Insert one layer's staging K/V via the paired jitted call,
    falling back per-name for exotic cache pytrees."""
    if set(layer) == {"k", "v"}:
        k, v = fn(layer["k"], layer["v"], src["k"], src["v"], *args)
        return {"k": k, "v": v}
    out = {}
    for name in layer:
        out[name], _ = fn(layer[name], layer[name], src[name],
                          src[name], *args)
    return out


def paged_supported(forwards):
    """True when every cacheable block speaks the paged decode step
    (``apply_step_paged``) — the scheduler otherwise falls back to the
    dense slot cache."""
    has = False
    for u in forwards:
        if hasattr(u, "init_cache"):
            has = True
            if not hasattr(u, "apply_step_paged"):
                return False
    return has


class SlotKVCache:
    """Per-layer dense slot-major K/V buffers + free-slot
    bookkeeping (the legacy layout; parity baseline for the paged
    cache)."""

    def __init__(self, forwards, max_slots, window):
        from veles_tpu import dtypes
        self.max_slots = int(max_slots)
        self.window = int(window)
        if self.max_slots < 1 or self.window < 2:
            raise ValueError("need max_slots >= 1 and window >= 2")
        self.caches = {
            i: u.init_cache(self.max_slots, self.window,
                            dtypes.compute_dtype())
            for i, u in enumerate(forwards)
            if hasattr(u, "init_cache")}
        if not self.caches:
            raise ValueError("chain has no cacheable blocks")
        # lowest slot first — keeps occupancy dense and debuggable
        self._free = list(range(self.max_slots - 1, -1, -1))

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def active_slots(self):
        return self.max_slots - len(self._free)

    def can_admit(self, total_tokens):
        """A dense slot reserves the full window row regardless of
        the request's length — a free slot is the only requirement."""
        return bool(self._free)

    def alloc(self, total_tokens=0):
        """Claim a free slot index, or None when all are busy."""
        return self._free.pop() if self._free else None

    def release(self, slot):
        slot = int(slot)
        if slot in self._free:
            raise ValueError("slot %d double-freed" % slot)
        self._free.append(slot)

    def insert(self, slot, row_caches, length=None):
        """Adopt a prefilled batch-1 staging row (serving/prefill.py
        output, width ≤ window) into ``slot``.  Rows the staging
        didn't cover are stale from the previous occupant — harmless:
        decode attends only over [0, len) and writes every position
        ≥ prompt_len itself, so stale K/V is never read."""
        s = jnp.int32(slot)
        w = self.window
        for i, layer in self.caches.items():
            src = {n: a[:, :w] if a.shape[1] > w else a
                   for n, a in row_caches[i].items()}
            self.caches[i] = _insert_layer(layer, src,
                                           _insert_row_pair, s)


class PagedKVCache:
    """Block-paged K/V pools + per-slot block tables.

    ``block_size`` tokens per block; ``kv_blocks`` — the pool's
    usable capacity in blocks (default: the dense equivalent,
    ``max_slots · ceil(window / block_size)``, so a default-sized pool
    admits everything the dense cache would).  ``window`` stays the
    per-request length bound (the positional-table limit), NOT a
    per-request memory reservation.

    ``kv_dtype`` — ``"fp32"`` (the compute-dtype pools above; parity
    baseline, byte-for-byte the PR 5 layout) or ``"int8"``: pools
    stored as int8 with per-row f32 dequant scales
    ([num_blocks, block_size], keys ``k_scale``/``v_scale``) living
    beside them in the same per-layer dict.  Scales are indexed by
    PHYSICAL block id exactly like the pools, so they follow blocks
    through every ownership move — prefix-cache donation, eviction,
    warm gather, preempt→resume — with no extra bookkeeping.
    Inserts quantize (``serving.kv_quant_insert_blocks``), the warm
    gather dequantizes (``serving.kv_quant_gather_blocks``), and the
    decode/verify steps quantize-on-scatter / dequant-on-gather in
    ``ops/paged_attention.py``."""

    def __init__(self, forwards, max_slots, window, block_size=16,
                 kv_blocks=None, kv_dtype="fp32", tp=None):
        from veles_tpu import dtypes
        self.max_slots = int(max_slots)
        self.window = int(window)
        self.block_size = int(block_size)
        if self.max_slots < 1 or self.window < 2:
            raise ValueError("need max_slots >= 1 and window >= 2")
        if self.block_size < 1:
            raise ValueError("need block_size >= 1")
        if kv_dtype not in ("fp32", "int8"):
            raise ValueError("kv_dtype must be 'fp32' or 'int8'")
        self.kv_dtype = kv_dtype
        self.blocks_per_slot = -(-self.window // self.block_size)
        self.capacity_blocks = int(
            kv_blocks or self.max_slots * self.blocks_per_slot)
        if self.capacity_blocks < 1:
            raise ValueError("need kv_blocks >= 1")
        num = self.capacity_blocks + 1          # + the trash block 0
        if kv_dtype == "int8":
            # int8 needs block-pool-aware units (the scale layout is
            # theirs to consume in apply_step_paged)
            missing = [type(u).__name__ for u in forwards
                       if hasattr(u, "init_cache")
                       and not hasattr(u, "init_block_pool")]
            if missing:
                raise ValueError(
                    "kv_dtype='int8' needs init_block_pool on every "
                    "cacheable block; missing on %s" % missing)
            self.pools = {
                i: u.init_block_pool(num, self.block_size,
                                     dtypes.compute_dtype(),
                                     kv_dtype="int8")
                for i, u in enumerate(forwards)
                if hasattr(u, "init_cache")}
        else:
            self.pools = {
                i: u.init_cache(num, self.block_size,
                                dtypes.compute_dtype())
                for i, u in enumerate(forwards)
                if hasattr(u, "init_cache")}
        if not self.pools:
            raise ValueError("chain has no cacheable blocks")
        #: tensor-parallel serving context (serving/tp.py) — pools
        #: shard HEAD-WISE over the mesh (each chip stores
        #: [num_blocks, block_size, d/tp]; scales replicate), so the
        #: per-chip HBM a kv_blocks budget costs drops by the mesh
        #: factor; the compiled steps read the ctx off the cache
        self.tp_ = tp
        if tp is not None:
            self.pools = tp.shard_pools(self.pools)
        self._free_slots = list(range(self.max_slots - 1, -1, -1))
        self._free_blocks = list(range(num - 1, 0, -1))
        #: host-side tables [max_slots, blocks_per_slot]; entries past
        #: a slot's live count stay 0 (the trash block)
        self.tables = numpy.zeros(
            (self.max_slots, self.blocks_per_slot), numpy.int32)
        self.n_blocks = numpy.zeros((self.max_slots,), numpy.int32)
        #: leading SHARED blocks per slot (prefix-cache residents the
        #: slot reads but does not own — release hands them back to
        #: the caller instead of the free list; decode never writes
        #: them because the cold offset starts past the shared range)
        self.n_shared = numpy.zeros((self.max_slots,), numpy.int32)

    # -- occupancy reads ------------------------------------------------

    @property
    def free_slots(self):
        return len(self._free_slots)

    @property
    def active_slots(self):
        return self.max_slots - len(self._free_slots)

    @property
    def free_blocks(self):
        return len(self._free_blocks)

    @property
    def used_blocks(self):
        return self.capacity_blocks - len(self._free_blocks)

    def bytes_per_token(self):
        """PER-CHIP HBM bytes ONE cached token costs across every
        layer's pools — the denominator of "streams per HBM dollar"
        (int8 pays ``2·d + 8`` per layer where the compute dtype pays
        ``2·d·itemsize``; reported in ``/serving/metrics`` and
        Prometheus as ``kv_bytes_per_token``).  Under tensor-parallel
        serving the K/V contribution divides by the mesh factor —
        each chip stores ``d/tp`` of every row — while the replicated
        scales still cost every chip their full byte."""
        shards = self.tp_.size if self.tp_ is not None else 1
        total = 0
        for layer in self.pools.values():
            for name, arr in layer.items():
                if name.endswith("_scale"):   # one scale per row
                    total += arr.dtype.itemsize
                else:
                    total += arr.shape[-1] * arr.dtype.itemsize \
                        // shards
        return int(total)

    def blocks_needed(self, total_tokens):
        return -(-max(int(total_tokens), 1) // self.block_size)

    def can_admit(self, total_tokens):
        """Memory-proportional admission: a free slot AND enough free
        blocks for the request's WHOLE budget (prompt + steps — the
        full reservation up front means decode can never starve for a
        block mid-flight)."""
        return bool(self._free_slots) \
            and self.blocks_needed(total_tokens) <= len(self._free_blocks)

    def alloc(self, total_tokens, shared=()):
        """Claim a slot and its full block budget, or None when slots
        or blocks are exhausted.  ``shared`` — block ids of an
        already-resident prompt prefix (prefix-cache hit): they head
        the table READ-ONLY and only ``need - len(shared)`` NEW
        blocks are claimed, which is how a warm prompt raises the
        concurrent-stream ceiling."""
        need = self.blocks_needed(total_tokens)
        shared = [int(b) for b in shared]
        if need > self.blocks_per_slot:
            raise ValueError(
                "request of %d tokens needs %d blocks > %d per-slot "
                "table width" % (total_tokens, need,
                                 self.blocks_per_slot))
        if len(shared) >= need:
            raise ValueError(
                "shared prefix of %d blocks must leave at least one "
                "private block of the %d-block budget"
                % (len(shared), need))
        if not self._free_slots \
                or need - len(shared) > len(self._free_blocks):
            return None
        slot = self._free_slots.pop()
        ids = shared + [self._free_blocks.pop()
                        for _ in range(need - len(shared))]
        self.tables[slot, :need] = ids
        self.tables[slot, need:] = 0
        self.n_blocks[slot] = need
        self.n_shared[slot] = len(shared)
        return slot

    def release(self, slot, donate=0):
        """Free a slot.  The leading shared blocks are handed BACK
        (never freed — the prefix cache still owns them); the next
        ``donate`` private blocks transfer ownership to the caller
        (a finishing request donating its prompt+generated prefix to
        the cache); the rest return to the free list.  Returns
        ``(shared_ids, donated_ids)``."""
        slot = int(slot)
        if slot in self._free_slots:
            raise ValueError("slot %d double-freed" % slot)
        n = int(self.n_blocks[slot])
        ns = int(self.n_shared[slot])
        donate = int(donate)
        if donate < 0 or ns + donate > n:
            raise ValueError(
                "donate=%d outside slot %d's %d private blocks"
                % (donate, slot, n - ns))
        row = [int(b) for b in self.tables[slot, :n]]
        shared, donated = row[:ns], row[ns:ns + donate]
        self._free_blocks.extend(reversed(row[ns + donate:]))
        self.tables[slot, :] = 0
        self.n_blocks[slot] = 0
        self.n_shared[slot] = 0
        self._free_slots.append(slot)
        return shared, donated

    def reclaim(self, ids):
        """Return blocks whose ownership left the slot machinery
        (prefix-cache evictions, duplicate donations) to the free
        list."""
        for b in ids:
            b = int(b)
            if b < 1 or b > self.capacity_blocks:
                raise ValueError("reclaim of invalid block %d" % b)
            if b in self._free_blocks:
                raise ValueError("block %d double-freed" % b)
            self._free_blocks.append(b)

    def take_free_blocks(self, n):
        """Claim ``n`` blocks off the free list OUTSIDE the slot
        machinery — the tiered-KV ingest path (host-tier promotion,
        peer prefix import) fills them via :meth:`import_blocks` and
        hands ownership straight to the prefix cache.  Returns the
        id list, or None when the free list is short (the ingest is
        best-effort and simply stays cold)."""
        n = int(n)
        if n < 0 or n > len(self._free_blocks):
            return None
        return [self._free_blocks.pop() for _ in range(n)]

    def check(self, resident=()):
        """Invariant sweep (tests): every block is exactly one of
        {trash, free, resident-in-the-prefix-cache,
        privately-owned-by-one-slot}, and every slot's SHARED prefix
        blocks appear in ``resident`` (they are counted once, as the
        cache's)."""
        resident = set(int(b) for b in resident)
        live = []
        for slot in range(self.max_slots):
            if slot not in self._free_slots:
                ns = int(self.n_shared[slot])
                row = [int(b) for b in
                       self.tables[slot, :self.n_blocks[slot]]]
                assert set(row[:ns]) <= resident, \
                    "slot %d shares non-resident blocks %s" \
                    % (slot, sorted(set(row[:ns]) - resident))
                live.extend(row[ns:])
        owned = live + [int(b) for b in self._free_blocks] \
            + sorted(resident)
        assert 0 not in owned, "trash block leaked into circulation"
        assert len(owned) == len(set(owned)), "block double-owned"
        assert len(owned) == self.capacity_blocks, \
            "block leaked: %d tracked of %d" % (len(owned),
                                                self.capacity_blocks)
        if self.kv_dtype == "int8":
            # scales-follow-blocks: every int8 pool must carry scale
            # arrays indexed by the same block axis (content checks
            # ride the gather/insert tests; this catches a layer
            # whose scales were dropped on a functional swap)
            for i, layer in self.pools.items():
                assert {"k", "v", "k_scale", "v_scale"} \
                    <= set(layer), \
                    "layer %s lost its scale arrays" % (i,)
                for name in ("k", "v"):
                    assert layer[name + "_scale"].shape \
                        == layer[name].shape[:2], \
                        "layer %s %s_scale shape drifted" % (i, name)

    def table_rows(self, slots, width):
        """The packed [len(slots), width] block-table batch the
        compiled paged step gathers through."""
        return self.tables[numpy.asarray(slots, numpy.intp), :width]

    def insert(self, slot, row_caches, length, from_block=0):
        """Block-scatter a prefilled batch-1 staging row (width a
        multiple of block_size, rows ≥ length zeroed) into ``slot``'s
        table blocks ``[from_block, ceil(length / block_size))``.
        ``from_block`` skips a warm shared prefix: those staging rows
        were GATHERED from the resident blocks (:meth:`load_staging`)
        and must not be written back through the shared table
        entries."""
        need = self.blocks_needed(length)
        f = int(from_block)
        if need > int(self.n_blocks[slot]):
            raise ValueError(
                "insert of %d tokens exceeds slot %d's %d-block "
                "budget" % (length, slot, int(self.n_blocks[slot])))
        if f >= need:
            raise ValueError(
                "from_block %d leaves nothing of the %d-block insert"
                % (f, need))
        ids = jnp.asarray(self.tables[slot, f:need])
        start = jnp.int32(f * self.block_size)
        for i, layer in self.pools.items():
            src = row_caches[i]
            wk = next(iter(src.values())).shape[1]
            if wk < need * self.block_size:
                raise ValueError(
                    "staging width %d < %d blocks x %d" %
                    (wk, need, self.block_size))
            if self.kv_dtype == "int8":
                k, v, sk, sv = _insert_blocks_q8_jit()(
                    layer["k"], layer["v"], layer["k_scale"],
                    layer["v_scale"], src["k"], src["v"], ids, start)
                self.pools[i] = {"k": k, "v": v, "k_scale": sk,
                                 "v_scale": sv}
            else:
                self.pools[i] = _insert_layer(layer, src,
                                              _insert_blocks,
                                              ids, start)

    def export_blocks(self, ids):
        """Gather blocks ``ids`` RAW out of every layer's pools for a
        disaggregated prefill→decode handoff: returns
        ``{layer: {"k", "v"[, "k_scale", "v_scale"]}}`` host numpy
        arrays, K/V shaped ``[len(ids), block_size, d]`` in the
        pool's storage dtype (int8 stays int8 — its scales travel in
        the same record, so the importing replica reproduces the
        resident bytes exactly, no dequant→requant noise)."""
        ids = jnp.asarray(numpy.asarray(ids, numpy.int32))
        fn = _export_blocks_jit()
        out = {}
        for i, layer in self.pools.items():
            if self.kv_dtype == "int8":
                k, v = fn(layer["k"], layer["v"], ids)
                sk, sv = fn(layer["k_scale"], layer["v_scale"], ids)
                got = {"k": k, "v": v, "k_scale": sk, "v_scale": sv}
            elif set(layer) == {"k", "v"}:
                k, v = fn(layer["k"], layer["v"], ids)
                got = {"k": k, "v": v}
            else:  # exotic cache pytrees: per-name self-pairing
                got = {}
                for name in layer:
                    got[name], _ = fn(layer[name], layer[name], ids)
            out[i] = {n: numpy.asarray(a) for n, a in got.items()}
        return out

    def import_blocks(self, ids, layers):
        """Scatter a :meth:`export_blocks` record into THIS cache's
        blocks ``ids`` (a decode-specialist adopting a prefill
        replica's finished KV): raw block contents land unconverted —
        the importing table's blocks end up byte-identical to the
        exporter's, scales included — so the decode loop attends over
        exactly the K/V the colocated path would have."""
        ids_j = jnp.asarray(numpy.asarray(ids, numpy.int32))
        n = int(len(ids))
        fn = _import_blocks_jit()
        for i, layer in self.pools.items():
            src = layers[i]
            ref = src["k"] if "k" in src else next(iter(src.values()))
            if ref.shape[0] != n or ref.shape[1] != self.block_size:
                raise ValueError(
                    "imported layer %s blocks %s do not fit %d x "
                    "block_size %d" % (i, ref.shape[:2], n,
                                       self.block_size))
            if self.kv_dtype == "int8":
                if "k_scale" not in src:
                    raise ValueError(
                        "int8 import needs k_scale/v_scale riding "
                        "the exported blocks")
                k, v = fn(layer["k"], layer["v"],
                          jnp.asarray(src["k"]), jnp.asarray(src["v"]),
                          ids_j)
                sk, sv = fn(layer["k_scale"], layer["v_scale"],
                            jnp.asarray(src["k_scale"]),
                            jnp.asarray(src["v_scale"]), ids_j)
                self.pools[i] = {"k": k, "v": v, "k_scale": sk,
                                 "v_scale": sv}
            elif set(layer) == {"k", "v"}:
                k, v = fn(layer["k"], layer["v"],
                          jnp.asarray(src["k"]), jnp.asarray(src["v"]),
                          ids_j)
                self.pools[i] = {"k": k, "v": v}
            else:
                got = {}
                for name in layer:
                    got[name], _ = fn(layer[name], layer[name],
                                      jnp.asarray(src[name]),
                                      jnp.asarray(src[name]), ids_j)
                self.pools[i] = got

    def load_staging(self, row_caches, ids):
        """Copy resident blocks ``ids`` (a matched prompt prefix)
        into the FRONT of a batch-1 staging row — the warm half of a
        prefix-cache admission: the cold tail's chunked prefill then
        attends over these rows exactly as if it had prefilled them
        itself (the resident K/V was produced by the identical
        computation).  Returns the updated staging dict."""
        if not len(ids):
            return row_caches
        ids = jnp.asarray(numpy.asarray(ids, numpy.int32))
        if self.kv_dtype == "int8":
            fn = _gather_blocks_q8_jit()
            out = {}
            for i, layer in self.pools.items():
                src = row_caches[i]
                k, v = fn(layer["k"], layer["v"], layer["k_scale"],
                          layer["v_scale"], src["k"], src["v"], ids)
                out[i] = {"k": k, "v": v}
            return out
        fn = _gather_blocks_jit()
        out = {}
        for i, layer in self.pools.items():
            src = row_caches[i]
            if set(layer) == {"k", "v"}:
                k, v = fn(layer["k"], layer["v"], src["k"], src["v"],
                          ids)
                out[i] = {"k": k, "v": v}
            else:  # exotic cache pytrees: per-name, pairing each
                # tensor with itself (same fallback as _insert_layer)
                got = {}
                for name in src:
                    got[name], _ = fn(layer[name], layer[name],
                                      src[name], src[name], ids)
                out[i] = got
        return out
