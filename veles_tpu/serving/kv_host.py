"""Host-RAM overflow tier for the radix prefix cache.

The radix cache (PR 9) lives in the device block pools, so its
capacity is whatever HBM live requests leave over — hit rate
collapses exactly when load rises.  This tier is the overflow: when
admission pressure evicts a refcount-0 block from the trie, the
scheduler first gathers its contents (``PagedKVCache.export_blocks``
— int8 stays int8, scales ride along) and parks them HERE, keyed by
the rolling digest of the token prefix the block completes
(:func:`serving.prefix_cache.chunk_digests`).  A later admission
whose prompt extends past its device-resident prefix into host
territory PROMOTES those blocks back into freshly claimed device
blocks and re-inserts them into the trie — the request then admits
through the ordinary warm path (staging gather + chunked prefill of
the cold tail).  Effective cache capacity becomes HBM + host RAM.

Storage is the :class:`memory.Array` host/device pair protocol with
only the host half populated: each demoted array is adopted as a
host mirror (``HOST_DIRTY``), and the promotion scatter is the
first — and only — device upload it ever gets.  The tier's bytes are
visible in ``memory.Watcher`` under :data:`WATCH_KEY`, bounded by a
byte budget with LRU eviction.

Consistency: a digest names a full token path, and each entry stores
its own chunk tokens, so a match re-verifies tokens level by level —
a crc32 collision degrades to a miss, never to wrong KV.  Evicting a
mid-chain entry orphans its descendants (the match walk breaks at
the gap); orphans are never touched again, so LRU ages them out.
Single-threaded like the trie: the scheduler loop owns every call.
"""

import numpy

from .. import memory
from .prefix_cache import chunk_digests

#: ``memory.Watcher`` accounting key for host-tier bytes.
WATCH_KEY = "host:kv-tier"


class _HostBlock:
    __slots__ = ("digest", "key", "depth", "layers", "nbytes",
                 "stamp")

    def __init__(self, digest, key, depth, layers, nbytes, stamp):
        self.digest = digest      # rolling digest of the full path
        self.key = key            # this block's block_size tokens
        self.depth = depth        # 0-based chunk index in the path
        self.layers = layers      # {chain idx: {name: memory.Array}}
        self.nbytes = nbytes
        self.stamp = stamp        # LRU tick of the last touch


class HostKVTier:
    """Byte-budgeted, LRU host store of demoted KV blocks."""

    def __init__(self, byte_budget, block_size):
        self.byte_budget = int(byte_budget)
        self.block_size = int(block_size)
        self._entries = {}        # digest -> _HostBlock
        self._clock = 0
        self.bytes = 0            # resident payload bytes (gauge)
        self.demotions = 0        # blocks accepted, cumulative
        self.promotions = 0       # blocks promoted out, cumulative
        self.evictions = 0        # blocks LRU-dropped, cumulative

    @property
    def blocks(self):
        return len(self._entries)

    def digests(self):
        """Every resident path digest — merged into the replica's
        cache-topology advertisement next to the trie's."""
        return list(self._entries)

    # -- demote ----------------------------------------------------------

    def put(self, path_tokens, layers):
        """Adopt one evicted block's contents.  ``path_tokens`` is
        the full token prefix the block completes (must be
        block-aligned); ``layers`` is ``export_blocks`` output for
        that single block — ``{chain idx: {name: [1, bs, d] numpy}}``.
        Returns True when adopted (False: over-budget singleton or
        unaligned path)."""
        bs = self.block_size
        if not path_tokens or len(path_tokens) % bs:
            return False
        held = {}
        nbytes = 0
        for i, layer in layers.items():
            held[int(i)] = row = {}
            for name, a in layer.items():
                arr = memory.Array(numpy.ascontiguousarray(a))
                row[str(name)] = arr
                nbytes += arr.mem.nbytes
        if nbytes > self.byte_budget:
            return False
        self._clock += 1
        digest = chunk_digests(path_tokens, bs)[-1]
        old = self._entries.pop(digest, None)
        if old is not None:
            self._drop(old)
        while self.bytes + nbytes > self.byte_budget:
            if not self._evict_lru():
                return False
        self._entries[digest] = _HostBlock(
            digest, tuple(int(t) for t in path_tokens[-bs:]),
            len(path_tokens) // bs - 1, held, nbytes, self._clock)
        self.bytes += nbytes
        self.demotions += 1
        memory.Watcher.alloc(WATCH_KEY, nbytes)
        return True

    # -- promote ---------------------------------------------------------

    def match(self, tokens, start_blocks, max_blocks=None):
        """The host extension of a device-resident prefix: entries
        for consecutive chunks of ``tokens`` starting at depth
        ``start_blocks``, token-verified level by level.  Entries are
        NOT removed — call :meth:`pop` once their promotion lands."""
        bs = self.block_size
        digs = chunk_digests(tokens, bs)
        stop = len(digs)
        if max_blocks is not None:
            stop = min(stop, int(start_blocks) + int(max_blocks))
        out = []
        self._clock += 1
        for d in range(int(start_blocks), stop):
            e = self._entries.get(digs[d])
            if e is None or e.depth != d or e.key != tuple(
                    int(t) for t in tokens[d * bs:(d + 1) * bs]):
                break
            e.stamp = self._clock
            out.append(e)
        return out

    def pop(self, entries):
        """Remove promoted entries (their contents now live in device
        blocks — keeping the host copy would double-count the budget;
        a later device eviction re-demotes them)."""
        for e in entries:
            if self._entries.pop(e.digest, None) is not None:
                self._drop(e)
                self.promotions += 1

    # -- budget ----------------------------------------------------------

    def _drop(self, entry):
        self.bytes -= entry.nbytes
        memory.Watcher.free(WATCH_KEY, entry.nbytes)

    def _evict_lru(self):
        victim = None
        for e in self._entries.values():
            if victim is None or e.stamp < victim.stamp:
                victim = e
        if victim is None:
            return False
        del self._entries[victim.digest]
        self._drop(victim)
        self.evictions += 1
        return True

    def clear(self):
        for e in list(self._entries.values()):
            self._drop(e)
        self._entries.clear()
