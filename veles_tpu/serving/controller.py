"""The fleet control plane — a decision loop over the machinery the
last four PRs shipped.

PR 14 gave the fleet eyes (federated metrics, the multi-window SLO
burn pair) and PR 15 reflexes (respawn, re-role for coverage), but
replica count, the prefill:decode specialist ratio and the KV-pressure
knobs all stayed static while load is not.  :class:`FleetController`
runs beside the router (same host, its own ticker thread — the
:class:`~veles_tpu.telemetry.alerts.AlertEngine` shape) and closes
three loops, every decision an auditable JSONL event plus
``veles_controller_*`` series:

- **replica autoscaling** — scale UP when the fast+slow SLO-burn
  pair fires (``slo_burn_*`` rules on the router's alert engine —
  the multi-window pair is precisely an autoscaler's up signal: fast
  enough to matter, slow enough to be real) or the mean per-replica
  queue depth crosses ``queue_high``; scale DOWN through the
  existing ``router.drain_replica`` → drained poll →
  :meth:`Fleet.retire` path (never a hard kill) only after
  ``quiet_ticks`` consecutive calm ticks with slot occupancy under
  ``occupancy_low``.  Hysteresis everywhere: each direction has its
  own cooldown, bounds are ``[min_replicas, max_replicas]``, and the
  ``controller_flapping`` alert rule watches the transition counter
  in case the thresholds are mis-tuned anyway.
- **role-proportion sizing** — PR 15's :meth:`Fleet.rebalance`
  restores role COVERAGE only (a pool must never be empty); this
  loop moves the RATIO: when decode slot occupancy outruns prefill
  queue pressure by more than ``role_deadband`` (or vice versa), the
  least-loaded surplus specialist restarts into the starved role via
  :meth:`Fleet.restart_as` — the same ``spawn(index, role)``
  machinery a coverage rebalance uses, and never the last member of
  a pool.
- **KV knob tuning** — sustained KV pressure over
  ``kv_pressure_high`` tightens every replica's admission shedding
  (``shed_block_factor`` down one ``shed_step`` through the
  admin-gated ``POST /serving/tune``, clamped to
  ``[shed_min, shed_max]``; pressure under ``kv_pressure_low``
  relaxes it back) and emits a ``recommend_kv_blocks`` audit event
  sizing the pool a restart should provision — recommendations are
  decisions an operator replays from the audit trail, never a live
  repool.

Since PR 17 the controller has a memory: when the router carries a
history store (:mod:`veles_tpu.telemetry.tsdb`), every tick reads a
smoothed ``history_window`` of fleet-merged KV pressure and goodput
instead of trusting one instantaneous sample — KV tuning acts on the
windowed average, ``recommend_kv_blocks`` sizes the pool from the
observed pressure *p95* (the percentile a provisioning decision
should survive, not the moment the tick happened to land on), and
every audit record carries the ``window`` stats it decided from.
With no store (or an empty window) each consumer falls back to the
instantaneous observation, so the controller never stalls on its own
telemetry.

Config ``root.common.controller.*``, default OFF — :meth:`start`
refuses to arm unless ``enabled`` is set, so a fleet never drives
itself without an operator's say-so.  The loop consumes only
thread-safe router surfaces (:meth:`Router.replica_state`, the alert
engine's ``firing()``) and actuates only through public fleet/router
methods, so every decision path is unit-testable by stubbing the
observation and actuation seams.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque

from veles_tpu.logger import Logger, events
from veles_tpu.telemetry import metrics

__all__ = ("FleetController",)


def _controller_conf(name, default):
    from veles_tpu.config import root
    return root.common.controller.get(name, default)


def _controller_series():
    return {
        "decisions": metrics.counter(
            "veles_controller_decisions_total",
            "control-plane decisions taken, by action (scale_up / "
            "scale_down / rerole / tune_shed / recommend_kv_blocks)",
            labelnames=("action",)),
        "transitions": metrics.counter(
            "veles_controller_scale_transitions_total",
            "replica-count scale transitions (up or down) — the "
            "controller_flapping alert rule watches increase() here"),
        "replicas": metrics.gauge(
            "veles_controller_replicas",
            "live replicas the controller observed on its last tick"),
        "ticks": metrics.counter(
            "veles_controller_ticks_total",
            "control-loop evaluation passes"),
    }


class FleetController(Logger):
    """The autoscaling / role-ratio / KV-tuning loop over one
    ``(router, fleet)`` pair (module docstring has the contract).
    ``start()`` arms the ticker thread only when
    ``root.common.controller.enabled``; ``tick()`` is one evaluation
    pass and is how tests drive the state machine directly."""

    def __init__(self, router, fleet, interval=None, tsdb=None):
        super(FleetController, self).__init__()
        self.router = router
        self.fleet = fleet
        #: explicit history store; None resolves the router's
        #: (lazily, per tick — the router builds its store at
        #: start(), usually after this constructor ran)
        self.tsdb = tsdb
        self.interval = float(
            _controller_conf("interval", 2.0)
            if interval is None else interval)
        #: bounded audit ring: the in-process "why did it scale?"
        #: record (every entry is ALSO a controller.decision JSONL
        #: event — the ring is the live view, the sink the archive)
        self.decisions = deque(
            maxlen=int(_controller_conf("audit_keep", 256)))
        self.ticks = 0
        self._quiet = 0              # consecutive calm ticks
        self._last_up = 0.0          # monotonic cooldown anchors
        self._last_down = 0.0
        self._last_rerole = 0.0
        self._last_tune = 0.0
        self._shed_factor = None     # last factor this loop pushed
        self._global = _controller_series()
        self._stop = threading.Event()
        self._lifecycle = threading.Lock()
        self._thread = None

    @staticmethod
    def enabled():
        """The arming knob (``root.common.controller.enabled``,
        default False): an unarmed controller observes nothing and
        acts never."""
        return bool(_controller_conf("enabled", False))

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if not self.enabled():
            self.info("controller not armed "
                      "(root.common.controller.enabled is off)")
            return self
        with self._lifecycle:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="fleet-controller")
                self._thread.start()
                self.info("fleet controller armed: tick %.2fs, "
                          "replicas [%d, %d]", self.interval,
                          int(_controller_conf("min_replicas", 1)),
                          int(_controller_conf("max_replicas", 4)))
        return self

    def stop(self):
        self._stop.set()
        with self._lifecycle:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(10)

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:   # the loop must outlive any bug
                self.warning("controller tick failed: %r", e)

    # -- observation -------------------------------------------------------

    def _observe(self):
        """One thread-safe fleet observation: the live (healthy,
        non-draining) replica views plus the aggregates every
        decision reads."""
        state = self.router.replica_state()
        live = [r for r in state["replicas"]
                if r.get("healthy") and not r.get("draining")]
        queues = [float(r.get("queue_depth") or 0) for r in live]
        active = sum(int(r.get("active_slots") or 0) for r in live)
        cap = sum(int(r.get("max_slots") or 0) for r in live)
        used = sum(int(r.get("kv_blocks_used") or 0) for r in live)
        free = sum(int(r.get("kv_blocks_free") or 0) for r in live)
        return {
            "live": live,
            "queue_mean": sum(queues) / len(queues) if queues
            else 0.0,
            "occupancy": active / cap if cap else 0.0,
            "kv_pressure": used / (used + free) if used + free
            else 0.0,
            "kv_blocks_total": used + free,
            "window": self._window_stats(),
        }

    def _window_stats(self):
        """Smoothed history over the router's fleet-merged store:
        ``history_window`` seconds of KV pressure (avg + p95) and
        goodput.  None when there is no store or no data yet — every
        consumer then falls back to the instantaneous sample, so the
        controller keeps working while its memory warms up."""
        store = self.tsdb if self.tsdb is not None \
            else getattr(self.router, "tsdb", None)
        if store is None:
            return None
        window = float(_controller_conf("history_window", 30.0))
        try:
            kv_avg = store.range("veles_serving_kv_pressure",
                                 window=window, agg="avg")
            kv_p95 = store.range("veles_serving_kv_pressure",
                                 window=window, agg="p95")
            goodput = store.range(
                "veles_serving_goodput_tokens_per_sec",
                window=window, agg="avg")
        except Exception as e:
            self.warning("history window read failed: %r", e)
            return None
        if kv_avg is None and kv_p95 is None and goodput is None:
            return None
        out = {"window_s": window}
        if kv_avg is not None:
            out["kv_pressure_avg"] = round(kv_avg, 4)
        if kv_p95 is not None:
            out["kv_pressure_p95"] = round(kv_p95, 4)
        if goodput is not None:
            out["goodput_avg"] = round(goodput, 3)
        return out

    def _burn_firing(self):
        """The firing SLO-burn rules on the router's alert engine —
        the ``slo_burn`` kind already requires BOTH its fast and
        slow windows over threshold, so one firing rule IS the
        multi-window pair agreeing."""
        engine = getattr(self.router, "alerts", None)
        if engine is None:
            return ()
        try:
            return tuple(sorted({str(row["rule"])
                                 for row in engine.firing()
                                 if str(row["rule"])
                                 .startswith("slo_burn")}))
        except Exception:
            return ()

    # -- the loop ----------------------------------------------------------

    def tick(self, now=None):
        """One evaluation pass; returns the structural decision it
        took (a dict from the audit ring) or None.  At most one
        structural action (scale or re-role) per tick — KV tuning
        rides along independently."""
        now = time.monotonic() if now is None else now
        self.ticks += 1
        self._global["ticks"].inc()
        try:
            obs = self._observe()
        except Exception as e:
            self.warning("controller observation failed: %r", e)
            return None
        self._global["replicas"].set(len(obs["live"]))
        burn = self._burn_firing()
        calm = not burn \
            and obs["queue_mean"] < float(
                _controller_conf("queue_high", 4.0)) \
            and obs["occupancy"] <= float(
                _controller_conf("occupancy_low", 0.3))
        self._quiet = self._quiet + 1 if calm else 0
        action = self._maybe_scale_up(obs, burn, now)
        if action is None:
            action = self._maybe_scale_down(obs, burn, now)
        if action is None:
            action = self._maybe_rerole(obs, now)
        self._maybe_tune(obs, now)
        return action

    # -- loop (a): replica autoscaling -------------------------------------

    def _maybe_scale_up(self, obs, burn, now):
        queue_high = float(_controller_conf("queue_high", 4.0))
        if not burn and obs["queue_mean"] < queue_high:
            return None
        if len(obs["live"]) >= int(
                _controller_conf("max_replicas", 4)):
            return None
        if now - self._last_up < float(
                _controller_conf("scale_up_cooldown", 10.0)):
            return None
        role = self._grow_role(obs)
        try:
            index = self.fleet.grow(role=role)
        except Exception as e:
            self.warning("scale-up spawn failed: %r", e)
            return None
        self._last_up = now
        self._quiet = 0
        return self._decide(
            "scale_up", index=index, role=role,
            reason="slo_burn" if burn else "queue_depth",
            burn_rules=list(burn),
            queue_mean=round(obs["queue_mean"], 3),
            replicas=len(obs["live"]) + 1,
            window=obs.get("window"))

    def _grow_role(self, obs):
        """The role a scale-up spawns with: None for homogeneous
        fleets; for specialist fleets, the phase under more pressure
        (decode slot occupancy vs prefill queueing)."""
        if not self.fleet.roles:
            return None
        pf_p, dc_p = self._role_pressures(obs)
        return "decode" if dc_p >= pf_p else "prefill"

    def _maybe_scale_down(self, obs, burn, now):
        if burn or self._quiet < int(
                _controller_conf("quiet_ticks", 5)):
            return None
        live = obs["live"]
        if len(live) <= int(_controller_conf("min_replicas", 1)):
            return None
        if now - self._last_down < float(
                _controller_conf("scale_down_cooldown", 30.0)):
            return None
        victim = self._drain_victim(live)
        if victim is None:
            return None
        index = self.fleet.index_of(victim["id"])
        if index is None:
            return None
        if not self._retire(victim, index):
            return None
        self._last_down = now
        self._quiet = 0
        return self._decide(
            "scale_down", index=index, replica=victim["id"],
            reason="quiet", occupancy=round(obs["occupancy"], 3),
            queue_mean=round(obs["queue_mean"], 3),
            replicas=len(live) - 1,
            window=obs.get("window"))

    def _drain_victim(self, live):
        """The replica a scale-down drains: least outstanding work,
        never the last live member of a specialist pool."""
        pools = {}
        for r in live:
            pools[r.get("role")] = pools.get(r.get("role"), 0) + 1
        candidates = [r for r in live
                      if not self.fleet.roles
                      or pools.get(r.get("role"), 0) >= 2]
        if not candidates:
            return None
        return min(candidates,
                   key=lambda r: (int(r.get("outstanding") or 0),
                                  int(r.get("queue_depth") or 0),
                                  r["id"]))

    def _retire(self, victim, index, timeout=30.0, poll=0.05):
        """The graceful half of scale-down: drain through the router
        (routing stops immediately), poll the replica's /healthz
        until in-flight work finished, then retire the fleet index —
        never a hard kill under live requests."""
        rid = victim["id"]
        try:
            self.router.drain_replica(rid)
        except Exception as e:
            self.warning("scale-down drain of %s failed: %r", rid, e)
            return False
        deadline = time.monotonic() + timeout
        url = "http://%s:%s/healthz" % (victim["host"],
                                        victim["port"])
        while time.monotonic() < deadline:
            try:
                health = self._get_json(url)
            except Exception:
                break            # replica already gone: retire it
            if health.get("drained") or not health.get("in_flight"):
                break
            time.sleep(poll)
        try:
            self.fleet.retire(index)
        except Exception as e:
            self.warning("retire of replica %d failed: %r", index, e)
            return False
        return True

    # -- loop (b): role-proportion sizing ----------------------------------

    def _role_pressures(self, obs):
        """Normalized (prefill, decode) pressure pair: prefill
        queue depth against ``queue_high`` vs decode slot occupancy
        (both ~[0, 1]; the deadband compares them directly)."""
        queue_high = max(1.0, float(
            _controller_conf("queue_high", 4.0)))
        pf = [r for r in obs["live"] if r.get("role") == "prefill"]
        dc = [r for r in obs["live"] if r.get("role") == "decode"]
        pf_q = [float(r.get("queue_depth") or 0) for r in pf]
        pf_p = (sum(pf_q) / len(pf_q) / queue_high) if pf_q else 0.0
        act = sum(int(r.get("active_slots") or 0) for r in dc)
        cap = sum(int(r.get("max_slots") or 0) for r in dc)
        dc_p = act / cap if cap else 0.0
        return pf_p, dc_p

    def _maybe_rerole(self, obs, now):
        if not self.fleet.roles:
            return None
        if now - self._last_rerole < float(
                _controller_conf("scale_up_cooldown", 10.0)):
            return None
        pf = [r for r in obs["live"] if r.get("role") == "prefill"]
        dc = [r for r in obs["live"] if r.get("role") == "decode"]
        if not pf or not dc:
            return None      # coverage is Fleet.rebalance()'s job
        pf_p, dc_p = self._role_pressures(obs)
        deadband = float(_controller_conf("role_deadband", 0.25))
        if dc_p - pf_p > deadband and len(pf) >= 2:
            donors, role = pf, "decode"
        elif pf_p - dc_p > deadband and len(dc) >= 2:
            donors, role = dc, "prefill"
        else:
            return None
        victim = min(donors,
                     key=lambda r: (int(r.get("outstanding") or 0),
                                    int(r.get("queue_depth") or 0),
                                    r["id"]))
        index = self.fleet.index_of(victim["id"])
        if index is None:
            return None
        try:
            self.fleet.restart_as(index, role)
        except Exception as e:
            self.warning("re-role of replica %d failed: %r",
                         index, e)
            return None
        self._last_rerole = now
        return self._decide(
            "rerole", index=index, replica=victim["id"], role=role,
            prefill_pressure=round(pf_p, 3),
            decode_pressure=round(dc_p, 3))

    # -- loop (c): KV knob tuning ------------------------------------------

    def _maybe_tune(self, obs, now):
        if not obs["live"] or now - self._last_tune < float(
                _controller_conf("scale_up_cooldown", 10.0)):
            return None
        high = float(_controller_conf("kv_pressure_high", 0.85))
        low = float(_controller_conf("kv_pressure_low", 0.5))
        step = float(_controller_conf("shed_step", 0.5))
        lo = float(_controller_conf("shed_min", 1.0))
        hi = float(_controller_conf("shed_max", 8.0))
        window = obs.get("window")
        # the smoothed window (when the history store has one) beats
        # the instantaneous sample: one tick landing on a transient
        # spike/trough must not whipsaw admission shedding
        pressure = window["kv_pressure_avg"] \
            if window and "kv_pressure_avg" in window \
            else obs["kv_pressure"]
        if pressure >= high:
            base = hi / 2.0 if self._shed_factor is None \
                else self._shed_factor
            target = max(lo, base - step)
        elif pressure <= low and self._shed_factor is not None:
            # only relax a knob this loop previously tightened — an
            # idle fleet is NOT a signal to loosen admission shedding
            target = min(hi, self._shed_factor + step)
        else:
            return None
        if pressure >= high:
            # sizing recommendation rides the audit trail only — a
            # pool repool needs a restart, which is the operator's
            # (or a future rolling-restart policy's) call.  Sized
            # from the OBSERVED pressure percentile when history is
            # available: a pool provisioned so the window's p95
            # lands at kv_pressure_high, not a flat fudge factor
            p95 = (window or {}).get("kv_pressure_p95")
            if p95 is not None and high > 0:
                blocks = int(-(-obs["kv_blocks_total"] * p95 // high))
            else:
                blocks = int(obs["kv_blocks_total"] * 1.25)
            self._decide(
                "recommend_kv_blocks", kv_blocks=blocks or None,
                kv_pressure=round(pressure, 3), window=window)
        if target == self._shed_factor:
            return None
        applied = [r["id"] for r in obs["live"]
                   if self._tune_replica(r, target)]
        self._last_tune = now
        if not applied:
            return None
        self._shed_factor = target
        return self._decide(
            "tune_shed", shed_block_factor=target,
            kv_pressure=round(pressure, 3), replicas=applied,
            window=window)

    def _tune_replica(self, view, factor):
        """POST /serving/tune to one replica (admin bearer when
        configured — the same trust path /drain uses)."""
        url = "http://%s:%s/serving/tune" % (view["host"],
                                             view["port"])
        headers = {"Content-Type": "application/json"}
        from veles_tpu.config import root
        token = root.common.api.get("admin_token", None)
        if token:
            headers["Authorization"] = "Bearer %s" % token
        try:
            req = urllib.request.Request(
                url, data=json.dumps(
                    {"shed_block_factor": factor}).encode(),
                headers=headers)
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                return resp.status == 200
        except Exception as e:
            self.warning("tune of %s failed: %r", view["id"], e)
            return False

    @staticmethod
    def _get_json(url, timeout=5.0):
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read().decode())
            except Exception:
                return {}

    # -- audit -------------------------------------------------------------

    def _decide(self, action, **detail):
        """One auditable decision: the bounded ring (the live "why
        did it scale?" view), the controller.decision JSONL event
        (the archive) and the veles_controller_* series (the
        dashboard) all record it."""
        rec = {"t": round(time.time(), 3), "tick": self.ticks,
               "action": action}
        rec.update({k: v for k, v in detail.items()
                    if v is not None})
        self.decisions.append(rec)
        self._global["decisions"].labels(action=action).inc()
        if action in ("scale_up", "scale_down"):
            self._global["transitions"].inc()
        events.record("controller.decision", "single",
                      cls="FleetController", **rec)
        self.info("controller decision: %s", rec)
        return rec

    def audit(self):
        """The decision ring, oldest first — the object half of the
        docs/fleet.md audit walkthrough."""
        return list(self.decisions)
