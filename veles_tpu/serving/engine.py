"""The shared compiled decode steps over serving slots.

ONE executable serves every mix of in-flight requests: per-slot
positions (slots at different decode depths), per-slot sampler
settings (temperature / top-k ride as traced vectors), and
per-REQUEST PRNG streams (token ``t`` of a request with seed ``s`` is
drawn with ``fold_in(key(s), t)`` — reproducible per seed no matter
which slot the request landed in or what traffic it shared the batch
with).

Two step families:

- :func:`slot_decode_step` — the legacy DENSE path
  (``apply_step_slots`` over a SlotKVCache).  Always runs the full
  ``max_slots`` batch; free slots decode garbage rows whose cache
  rows the next occupant's attention never reads.
- :func:`paged_decode_step` — the PAGED path (``apply_step_paged``
  over a PagedKVCache): the scheduler PACKS only the active slots
  into a power-of-two *occupancy bucket* ``B`` and bounds the
  attended range by a power-of-two *block bucket* ``T`` over the
  deepest active slot, so a half-empty batch of shallow requests
  pays neither full-batch nor full-window compute.  Executables are
  cached per (chain, B, T) — O(log slots · log window) variants.
  Sampling is row-wise (per-request keys), so token streams are
  independent of packing order.
"""

import functools

import jax
import jax.numpy as jnp

from veles_tpu.models.generate import (
    _StepClosure, _arch_sig, _device_params)
from veles_tpu.telemetry import track_jit


def sample_slots(logits, temps, topks, keys):
    """Per-slot next-token sampler: rows with ``temps[n] == 0`` take
    the greedy argmax; sampling rows draw categorical(logits / temp)
    restricted to each row's top-k (0 = full vocab; ties with the
    k-th value stay in, matching ``generate``'s masking)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    v = logits.shape[-1]
    z = logits / jnp.maximum(temps, 1e-6)[:, None]
    zs = jnp.sort(z, axis=-1)
    kth = jnp.take_along_axis(
        zs, jnp.clip(v - topks, 0, v - 1)[:, None], axis=-1)
    z = jnp.where((topks[:, None] > 0) & (z < kth), -jnp.inf, z)
    drawn = jax.vmap(jax.random.categorical)(keys, z)
    return jnp.where(temps > 0, drawn.astype(jnp.int32), greedy)


def _fold_keys(seeds, counts):
    """Per-request stream keys: fold each request's draw counter into
    its seed-derived base key."""
    return jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.key(s), c))(
            seeds, counts)


def sample_first(logits, temps, topks, seeds, counts):
    """Post-prefill token sampler over the last-position logits:
    draw ``counts[n]`` of each request's stream — 0 for a fresh
    admission, ``len(generated)`` for a preempted request resuming
    after a re-prefill of prompt + prefix (the SAME key fold the
    decode step would have used, so the resumed stream is
    bit-identical to the uninterrupted one)."""
    keys = _fold_keys(seeds, counts)
    return sample_slots(logits, temps, topks, keys)


_sample_first_jit = track_jit("serving.sample_first",
                              jax.jit(sample_first))


def _make_step(forwards):
    cacheable = frozenset(i for i, u in enumerate(forwards)
                          if hasattr(u, "init_cache"))

    def step(params, toks, pos, temps, topks, seeds, counts, caches):
        h = toks
        out = dict(caches)
        for i, u in enumerate(forwards):
            if i in cacheable:
                h, out[i] = u.apply_step_slots(params[i], h, pos,
                                               caches[i])
            elif hasattr(u, "apply_step_slots"):
                h = u.apply_step_slots(params[i], h, pos)
            else:
                h = u.apply(params[i], h)
        logits = h[:, 0].astype(jnp.float32)
        keys = _fold_keys(seeds, counts)
        return sample_slots(logits, temps, topks, keys), out
    return step


@functools.lru_cache(maxsize=16)
def _step_cached(cache_key, closure):
    return track_jit("serving.slot_step", jax.jit(closure.fn))


def clear_step_cache():
    """Drop the compiled slot/paged-step caches (entries pin the
    chain's units — same lifetime note as
    ``generate.clear_decode_caches``)."""
    _step_cached.cache_clear()
    _paged_step_cached.cache_clear()
    _paged_step_tp_cached.cache_clear()
    _verify_step_cached.cache_clear()


def slot_decode_step(forwards, cache, toks, pos, temps, topks, seeds,
                     counts):
    """Run ONE decode step over every slot of ``cache``
    (:class:`serving.kv_slots.SlotKVCache`, updated in place).

    ``toks`` [S, 1] — each slot's last token; ``pos`` [S] — its
    sequence index (length - 1); ``temps``/``topks`` [S] — per-slot
    sampler settings; ``seeds``/``counts`` [S] — per-request PRNG
    stream (seed and draw counter for THIS step's token).  Returns the
    [S] next tokens (device array — callers ``numpy.asarray`` it)."""
    from veles_tpu import dtypes
    params = _device_params(forwards)
    cache_key = (_arch_sig(forwards), cache.max_slots, cache.window,
                 str(dtypes.compute_dtype()),
                 str(dtypes.matmul_precision()))
    fn = _step_cached(cache_key, _StepClosure(_make_step(forwards)))
    nxt, cache.caches = fn(
        params, jnp.asarray(toks, jnp.int32),
        jnp.asarray(pos, jnp.int32),
        jnp.asarray(temps, jnp.float32),
        jnp.asarray(topks, jnp.int32),
        jnp.asarray(seeds, jnp.uint32),
        jnp.asarray(counts, jnp.int32), cache.caches)
    return nxt


def hidden_supported(forwards):
    """True when the chain ends in a position-wise vocab head over a
    [batch, seq, d] hidden stream — the shape the optional
    hidden-state output lane (``want_hidden``) taps for the
    model-based draft head (serving/draft.py): the lane returns the
    input of the FINAL unit, i.e. the target's last hidden state."""
    if len(forwards) < 2:
        return False
    last = forwards[-1]
    return getattr(last, "DECODE_POINTWISE", False) \
        and not hasattr(last, "init_cache")


def _make_paged_step(forwards, want_hidden=False):
    cacheable = frozenset(i for i, u in enumerate(forwards)
                          if hasattr(u, "init_cache"))
    last = len(forwards) - 1

    def step(params, toks, pos, tables, temps, topks, seeds, counts,
             pools):
        h = toks
        hid = None
        out = dict(pools)
        for i, u in enumerate(forwards):
            if want_hidden and i == last:
                # the final unit's INPUT is the target's last hidden
                # state — what the draft head conditions on
                hid = h.astype(jnp.float32)
            if i in cacheable:
                h, out[i] = u.apply_step_paged(params[i], h, pos,
                                               tables, pools[i])
            elif hasattr(u, "apply_step_slots"):
                h = u.apply_step_slots(params[i], h, pos)
            else:
                h = u.apply(params[i], h)
        logits = h[:, 0].astype(jnp.float32)
        keys = _fold_keys(seeds, counts)
        nxt = sample_slots(logits, temps, topks, keys)
        if want_hidden:
            return nxt, hid[:, 0], out
        return nxt, out
    return step


@functools.lru_cache(maxsize=64)
def _paged_step_cached(cache_key, closure):
    return track_jit("serving.paged_step", jax.jit(closure.fn))


def overlap_supported(forwards):
    """True when every cacheable block in the chain speaks the
    per-shard decode body (``apply_step_paged_local``) the
    collective-overlap path is built from — the gate
    ``root.common.serving.tp_overlap`` checks before swapping the
    GSPMD step for the explicit shard_map one."""
    has = False
    for u in forwards:
        if hasattr(u, "init_cache"):
            has = True
            if not hasattr(u, "apply_step_paged_local"):
                return False
    return has


def _make_paged_step_tp(forwards, ctx, pools, want_hidden=False):
    """The EXPLICIT-collective tp decode step: the same math as
    :func:`_make_paged_step` under a tp mesh, but written per-shard
    through ``shard_map`` so each block's row-parallel reductions are
    explicit collective-permute / all-gather ops
    (serving/tp.tp_allreduce) instead of GSPMD-inserted all-reduces.
    Explicit collectives let the compiler START the cross-chip hop
    while the K/V pool writeback (data-independent of the reduction)
    proceeds — the overlap the serialized auto-partitioned step never
    gets.  tp=2 reduces by a single ppermute+add (bit-identical to
    psum: two-operand float addition is order-free), wider meshes
    all-gather and sum in fixed shard order (deterministic, same
    value on every shard)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    size = ctx.size
    cacheable = frozenset(i for i, u in enumerate(forwards)
                          if hasattr(u, "init_cache"))
    last = len(forwards) - 1
    pspecs = {}
    for i, u in enumerate(forwards):
        spec_fn = getattr(u, "tp_param_spec", None)
        layer = {}
        for name in u.param_arrays():
            spec = spec_fn(name, size) if spec_fn is not None \
                else None
            layer[name] = spec if spec is not None else P()
        pspecs[i] = layer
    lspecs = {}
    for i, layer in pools.items():
        lspecs[i] = {
            name: P(None, None, "tp")
            if not name.endswith("_scale") and a.ndim == 3
            and a.shape[-1] % size == 0 else P()
            for name, a in layer.items()}

    def body(params, toks, pos, tables, temps, topks, seeds, counts,
             pools_):
        h = toks
        hid = None
        out = dict(pools_)
        for i, u in enumerate(forwards):
            if want_hidden and i == last:
                hid = h.astype(jnp.float32)
            if i in cacheable:
                h, out[i] = u.apply_step_paged_local(
                    params[i], h, pos, tables, pools_[i], size)
            elif hasattr(u, "apply_step_slots"):
                h = u.apply_step_slots(params[i], h, pos)
            else:
                h = u.apply(params[i], h)
        logits = h[:, 0].astype(jnp.float32)
        keys = _fold_keys(seeds, counts)
        nxt = sample_slots(logits, temps, topks, keys)
        if want_hidden:
            return nxt, hid[:, 0], out
        return nxt, out

    rep = P()
    in_specs = (pspecs, rep, rep, rep, rep, rep, rep, rep, lspecs)
    out_specs = (rep, rep, lspecs) if want_hidden else (rep, lspecs)
    return shard_map(body, mesh=ctx.mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


@functools.lru_cache(maxsize=32)
def _paged_step_tp_cached(cache_key, closure):
    return track_jit("serving.paged_step_tp", jax.jit(closure.fn))


def paged_decode_step(forwards, cache, toks, pos, tables, temps,
                      topks, seeds, counts, want_hidden=False):
    """Run ONE decode step over a PACKED batch of active slots
    against ``cache`` (:class:`serving.kv_slots.PagedKVCache`,
    updated in place).

    All arrays are packed to the caller's occupancy bucket ``B``
    (padding rows: token 0, position 0, an all-zero table — they
    write into and read from the reserved trash block): ``toks``
    [B, 1], ``pos``/``temps``/``topks``/``seeds``/``counts`` [B],
    ``tables`` [B, T] physical block ids (T·block_size must cover
    ``max(pos) + 1``).  Returns the [B] next tokens; the caller maps
    packed rows back to its slots.  ``want_hidden`` additionally
    returns the [B, d] f32 last hidden state (the final unit's
    input) — the model-based draft head's conditioning
    (serving/draft.py); the flag keys the executable cache, so
    hidden-on and hidden-off never share a trace.

    A cache built with a tensor-parallel context (``cache.tp_`` —
    serving/tp.py) runs the step SPMD over the tp mesh: params ride
    pre-sharded Megatron-style, the pools head-wise, and the
    executable cache keys on the mesh size so tp on/off never share
    a trace.  With ``root.common.serving.tp_overlap`` set (and every
    cacheable block speaking the shard_map step — see
    ``overlap_supported``) the step compiles through the EXPLICIT
    collective path instead of GSPMD auto-insertion: per-shard block
    bodies combine their row-parallel partial sums with
    collective-permute / all-gather reductions the compiler can
    issue asynchronously, overlapping the cross-chip hop with the
    K/V pool writeback."""
    from veles_tpu import dtypes
    from veles_tpu.config import root
    ctx = getattr(cache, "tp_", None)
    params = ctx.device_params(forwards) if ctx is not None \
        else _device_params(forwards)
    tables = jnp.asarray(tables, jnp.int32)
    b, t = tables.shape
    # fp32 pools only: the int8 pool's per-row amax must reduce over
    # the FULL feature axis (GSPMD does that collectively); a
    # per-shard body would compute shard-local scales
    overlap = bool(ctx is not None
                   and root.common.serving.get("tp_overlap", False)
                   and getattr(cache, "kv_dtype", "fp32") == "fp32"
                   and overlap_supported(forwards))
    cache_key = (_arch_sig(forwards), b, t, cache.block_size,
                 cache.capacity_blocks,
                 getattr(cache, "kv_dtype", "fp32"),
                 ctx.size if ctx is not None else 1,
                 bool(want_hidden), overlap,
                 str(dtypes.compute_dtype()),
                 str(dtypes.matmul_precision()))
    if overlap:
        fn = _paged_step_tp_cached(
            cache_key, _StepClosure(_make_paged_step_tp(
                forwards, ctx, cache.pools,
                want_hidden=want_hidden)))
    else:
        fn = _paged_step_cached(
            cache_key, _StepClosure(_make_paged_step(
                forwards, want_hidden=want_hidden)))
    got = fn(
        params, jnp.asarray(toks, jnp.int32),
        jnp.asarray(pos, jnp.int32), tables,
        jnp.asarray(temps, jnp.float32),
        jnp.asarray(topks, jnp.int32),
        jnp.asarray(seeds, jnp.uint32),
        jnp.asarray(counts, jnp.int32), cache.pools)
    if want_hidden:
        nxt, hid, cache.pools = got
        return nxt, hid
    nxt, cache.pools = got
    return nxt


def _make_verify_step(forwards, want_hidden=False):
    cacheable = frozenset(i for i, u in enumerate(forwards)
                          if hasattr(u, "init_cache"))
    last = len(forwards) - 1

    def step(params, toks, pos, lens, tables, temps, topks, seeds,
             counts, pools):
        h = toks
        hid = None
        out = dict(pools)
        for i, u in enumerate(forwards):
            if want_hidden and i == last:
                hid = h.astype(jnp.float32)
            if i in cacheable:
                h, out[i] = u.apply_verify_paged(
                    params[i], h, pos, lens, tables, pools[i])
            elif hasattr(u, "apply_verify_slots"):
                h = u.apply_verify_slots(params[i], h, pos)
            else:
                h = u.apply(params[i], h)
        b, k1, v = h.shape
        logits = h.astype(jnp.float32).reshape(b * k1, v)
        # position j of row n draws stream token counts[n] + j — the
        # EXACT key a sequential decode of the accepted prefix would
        # fold, which is what makes acceptance distribution-exact
        keys = jax.vmap(
            lambda s, c: jax.vmap(
                lambda j: jax.random.fold_in(jax.random.key(s),
                                             c + j))(jnp.arange(k1)))(
            seeds, counts)
        nxt = sample_slots(logits, jnp.repeat(temps, k1),
                           jnp.repeat(topks, k1),
                           keys.reshape(b * k1))
        if want_hidden:
            return nxt.reshape(b, k1), hid, out
        return nxt.reshape(b, k1), out
    return step


@functools.lru_cache(maxsize=64)
def _verify_step_cached(cache_key, closure, donate=False):
    # the fused/int8 verify paths take the pool update off the
    # attention's critical path (ops/paged_attention.py), so the pool
    # buffers can be DONATED — the scatter lands in place instead of
    # copying the whole pool every step.  Safe: the caller swaps
    # cache.pools for the returned pools immediately (the donated
    # arrays are never read again).  The legacy two-pass executable
    # keeps the PR 9 no-donation behavior byte-for-byte.
    return track_jit("serving.verify_step", jax.jit(
        closure.fn, donate_argnums=(9,) if donate else ()))


def verify_step_paged(forwards, cache, toks, pos, lens, tables,
                      temps, topks, seeds, counts,
                      want_hidden=False):
    """Score a PACKED batch of speculative token runs in ONE model
    pass against ``cache`` (:class:`serving.kv_slots.PagedKVCache`,
    updated in place) — the batched verify step of speculative
    decoding.

    ``toks`` [B, K1] — row n's pending token followed by its drafted
    tokens (padded past ``lens[n]``); ``pos`` [B] — the sequence
    index of each row's pending token; ``lens`` [B] — real positions
    per row (1 = no drafts, i.e. a plain decode step riding the
    verify batch); ``tables``/``temps``/``topks``/``seeds`` as in
    :func:`paged_decode_step`; ``counts`` [B] — the draw counter of
    the FIRST sampled token (position j draws ``counts + j``).

    Returns [B, K1] next tokens: entry (n, j) is the token a
    sequential decode would emit after row n's context extended by
    its first j drafted tokens — the host accepts the longest prefix
    where draft j matches sample j-1 (plus the first non-matching
    sample, the "free" correction token), which reproduces the
    spec-off stream bit-for-bit for greedy AND per-seed sampling.
    ``want_hidden`` additionally returns the [B, K1, d] f32 hidden
    states (the final unit's input at every scored position) — after
    accepting L tokens the scheduler carries row position L-1's
    hidden into the next iteration's model-based draft."""
    from veles_tpu import dtypes
    from veles_tpu.config import root
    ctx = getattr(cache, "tp_", None)
    params = ctx.device_params(forwards) if ctx is not None \
        else _device_params(forwards)
    tables = jnp.asarray(tables, jnp.int32)
    toks = jnp.asarray(toks, jnp.int32)
    b, t = tables.shape
    k1 = toks.shape[1]
    # kv_dtype and the fused-verify knob both change the traced
    # verify body (TransformerBlock.apply_verify_paged reads them at
    # trace time) — they must key the executable or a toggle would
    # silently reuse the stale trace; the tp mesh size keys it too
    # (sharded params/pools compile a different SPMD program)
    kv_dtype = getattr(cache, "kv_dtype", "fp32")
    fused = bool(root.common.serving.get("fused_verify", False))
    cache_key = (_arch_sig(forwards), b, k1, t, cache.block_size,
                 cache.capacity_blocks, kv_dtype, fused,
                 ctx.size if ctx is not None else 1,
                 bool(want_hidden),
                 str(dtypes.compute_dtype()),
                 str(dtypes.matmul_precision()))
    fn = _verify_step_cached(
        cache_key,
        _StepClosure(_make_verify_step(forwards,
                                       want_hidden=want_hidden)),
        donate=fused or kv_dtype == "int8")
    got = fn(
        params, toks, jnp.asarray(pos, jnp.int32),
        jnp.asarray(lens, jnp.int32), tables,
        jnp.asarray(temps, jnp.float32),
        jnp.asarray(topks, jnp.int32),
        jnp.asarray(seeds, jnp.uint32),
        jnp.asarray(counts, jnp.int32), cache.pools)
    if want_hidden:
        nxt, hid, cache.pools = got
        return nxt, hid
    nxt, cache.pools = got
    return nxt


def verify_supported(forwards):
    """True when every cacheable block speaks the paged verify step
    (``apply_verify_paged``) and every other sequence-positioned unit
    can place a width-k run (``apply_verify_slots`` or position-
    wise) — the gate speculative decoding checks before enabling."""
    has = False
    for u in forwards:
        if hasattr(u, "init_cache"):
            has = True
            if not hasattr(u, "apply_verify_paged"):
                return False
        elif hasattr(u, "apply_step_slots") \
                and not hasattr(u, "apply_verify_slots"):
            return False
    return has


def first_tokens(last_logits, temps, topks, seeds, counts=None):
    """Sample each admitted request's next token from its prefill
    logits ([k, vocab] f32) — draw ``counts`` of its stream (default
    0, the fresh-admission case; a preempt-resume passes its
    generated-prefix length)."""
    if counts is None:
        counts = [0] * len(seeds)
    return _sample_first_jit(
        jnp.asarray(last_logits, jnp.float32),
        jnp.asarray(temps, jnp.float32),
        jnp.asarray(topks, jnp.int32),
        jnp.asarray(seeds, jnp.uint32),
        jnp.asarray(counts, jnp.int32))
