"""Cross-request radix prefix cache over the paged KV block pools.

SGLang-lineage (RadixAttention, Zheng et al.): finished requests
DONATE the KV blocks of their prompt + generated stream into a trie
keyed on ``block_size``-token chunks, and a joining request walks the
trie with its prompt — every matched chunk is a block of K/V it does
NOT have to prefill and does NOT have to claim from the free pool.
Repeated system prompts, re-submitted conversations and shared
few-shot preambles then cost near-zero TTFT (only the cold tail
prefills, through the chunked-prefill path) and admit MORE
concurrent streams (matched blocks are shared, refcounted, and
counted once).

Ownership contract with :class:`serving.kv_slots.PagedKVCache`:

- blocks resident here are OUT of the cache's free list — the trie
  owns them (``resident_blocks()`` feeds ``PagedKVCache.check``);
- a match REFCOUNTS every node on the path; the scheduler releases
  the handle when the request leaves its slot.  Refcounted blocks
  are pinned: evicting one raises, and so does a double release;
- eviction is LRU over refcount-0 LEAVES only (an inner node is
  reachable prefix state for its children — the trie never orphans
  a path), freeing blocks back to the pool under admission pressure;
- matched blocks head a slot's table READ-ONLY: the scheduler starts
  every write (cold-tail prefill, decode, verify) past the shared
  range, so sharing needs no copy-on-write.

Single-threaded like the block cache: the scheduler's decode loop
owns every mutating call; the lock-free counters read by metrics are
monitoring-grade.

Digests: the fleet tier (tiered KV, PR 19) identifies a resident
prefix by a ROLLING crc32 over its block-aligned token chunks —
``chunk_digests`` below is the one shared definition (scheduler
advertisement, host-tier keys, and router topology lookups must all
agree bit-for-bit).  crc32 is 32-bit, so a digest match is a HINT:
every consumer re-verifies against actual tokens (the host tier
stores them; a stale router hint just yields a 404'd peer fetch).
"""

import zlib


def chunk_digests(tokens, block_size, max_depth=None):
    """Rolling digests of ``tokens`` at block granularity: entry i is
    the crc32 of chunks 0..i chained (chunk i's canonical bytes,
    seeded with digest i-1).  The fleet-wide name of the prefix
    ``tokens[:(i + 1) * block_size]``."""
    bs = int(block_size)
    n = len(tokens) // bs
    if max_depth is not None:
        n = min(n, int(max_depth))
    out, d = [], 0
    for i in range(n):
        chunk = tokens[i * bs:(i + 1) * bs]
        d = zlib.crc32(
            (",".join(str(int(t)) for t in chunk)).encode("ascii"), d)
        out.append(d)
    return out


class _Node:
    __slots__ = ("key", "block", "refs", "children", "parent",
                 "stamp")

    def __init__(self, key, block, parent, stamp):
        self.key = key            # the block's block_size tokens
        self.block = int(block)   # physical block id it owns
        self.refs = 0             # active slots reading through it
        self.children = {}        # token-tuple -> _Node
        self.parent = parent
        self.stamp = stamp        # LRU tick of the last touch


class MatchHandle:
    """The pinned path a :meth:`RadixPrefixCache.match` returned —
    holds the matched nodes (refcounted until released) and exposes
    their block ids in prefix order."""

    __slots__ = ("nodes", "released")

    def __init__(self, nodes):
        self.nodes = nodes
        self.released = False

    @property
    def blocks(self):
        return [n.block for n in self.nodes]

    def __len__(self):
        return len(self.nodes)


class RadixPrefixCache:
    """Trie of donated KV blocks keyed on token-block boundaries."""

    def __init__(self, block_size):
        self.block_size = int(block_size)
        if self.block_size < 1:
            raise ValueError("need block_size >= 1")
        self._root = {}           # token-tuple -> _Node
        self._clock = 0
        self._resident = 0        # owned blocks (gauge)
        self.hits = 0             # matches with >= 1 block
        self.misses = 0
        self.hit_blocks = 0       # blocks served warm, cumulative
        self.evictions = 0        # blocks evicted, cumulative

    # -- reads -----------------------------------------------------------

    @property
    def resident(self):
        return self._resident

    def resident_blocks(self):
        """Every block id the trie owns (PagedKVCache.check feed)."""
        out = []
        stack = list(self._root.values())
        while stack:
            n = stack.pop()
            out.append(n.block)
            stack.extend(n.children.values())
        return out

    def shared_blocks(self):
        """Blocks currently pinned by at least one active request."""
        total = 0
        stack = list(self._root.values())
        while stack:
            n = stack.pop()
            if n.refs:
                total += 1
            stack.extend(n.children.values())
        return total

    def evictable_blocks(self):
        """How many blocks :meth:`evict` could free right now (the
        admission headroom on top of the free list).  Counts every
        refcount-0 block whose SUBTREE holds no pinned node — leaf
        eviction peels such a subtree bottom-up, so the whole chain
        is reachable headroom for one admission."""
        def sweep(node):
            free, pinned = 0, node.refs > 0
            for c in node.children.values():
                f, p = sweep(c)
                free += f
                pinned = pinned or p
            if not pinned:
                free += 1
            return free, pinned
        return sum(sweep(n)[0] for n in self._root.values())

    def peek(self, tokens, max_blocks=None):
        """How many leading blocks of ``tokens`` are resident —
        :meth:`match` without pinning (admission sizing)."""
        return len(self._walk(tokens, max_blocks))

    def resident_prefix(self, tokens, max_blocks=None):
        """Block ids of the resident leading chunks of ``tokens`` —
        :meth:`match` without pinning or hit/miss accounting.  For
        loop-thread probes that read the blocks synchronously (the
        tiered-KV prefix export and the promotion depth check): no
        other mutation can interleave, so pins would be dead
        weight and the stats would double-count the admission's
        own lookup."""
        return [n.block for n in self._walk(tokens, max_blocks)]

    def path_digests(self, max_entries=1024):
        """Rolling digests (:func:`chunk_digests`) of every resident
        path, breadth-first so shallow — most shareable — prefixes
        survive the cap.  This is the replica's cache-topology
        advertisement: the router matches a prompt's own digests
        against these to find the longest resident prefix fleet-wide."""
        out = []
        queue = [(0, n) for n in self._root.values()]
        while queue and len(out) < int(max_entries):
            next_q = []
            for seed, node in queue:
                d = zlib.crc32(
                    (",".join(str(int(t)) for t in node.key))
                    .encode("ascii"), seed)
                out.append(d)
                if len(out) >= int(max_entries):
                    break
                next_q.extend((d, c) for c in node.children.values())
            queue = next_q
        return out

    def _path_tokens(self, node):
        """The full token prefix a node's block completes (root keys
        concatenated) — the demotion path's host-tier key."""
        keys = []
        while node is not None:
            keys.append(node.key)
            node = node.parent
        out = []
        for key in reversed(keys):
            out.extend(key)
        return tuple(out)

    # -- match / release -------------------------------------------------

    def _chunks(self, tokens, max_blocks=None):
        bs = self.block_size
        n = len(tokens) // bs
        if max_blocks is not None:
            n = min(n, int(max_blocks))
        return [tuple(tokens[i * bs:(i + 1) * bs]) for i in range(n)]

    def _walk(self, tokens, max_blocks=None):
        nodes = []
        level = self._root
        for key in self._chunks(tokens, max_blocks):
            node = level.get(key)
            if node is None:
                break
            nodes.append(node)
            level = node.children
        return nodes

    def match(self, tokens, max_blocks=None):
        """Longest-prefix match at block granularity: returns a
        :class:`MatchHandle` whose blocks hold the K/V of
        ``tokens[:len(handle) * block_size]``.  Every matched node's
        refcount is raised until :meth:`release`.  ``max_blocks``
        caps the walk (the scheduler always leaves >= 1 cold token so
        the request still produces first-token logits)."""
        self._clock += 1
        nodes = self._walk(tokens, max_blocks)
        for n in nodes:
            n.refs += 1
            n.stamp = self._clock
        if nodes:
            self.hits += 1
            self.hit_blocks += len(nodes)
        else:
            self.misses += 1
        return MatchHandle(nodes)

    def release(self, handle):
        """Unpin a match.  Releasing twice — the shared-block double
        free — raises instead of silently corrupting refcounts."""
        if handle.released:
            raise ValueError("match handle double-released")
        handle.released = True
        for n in handle.nodes:
            if n.refs < 1:
                raise ValueError(
                    "shared block %d double-freed (refcount underflow)"
                    % n.block)
            n.refs -= 1

    # -- insert / evict --------------------------------------------------

    def insert(self, tokens, block_ids):
        """Donate the blocks of a finished sequence: ``block_ids[i]``
        holds the K/V of token chunk i.  Chunks already resident keep
        their incumbent block — the donated duplicate is REJECTED and
        returned for the caller to free (``PagedKVCache.reclaim``);
        new chunks take ownership of their donated block.  Returns
        ``(taken, rejected)`` id lists."""
        self._clock += 1
        taken, rejected = [], []
        level, parent = self._root, None
        for key, bid in zip(self._chunks(tokens), block_ids):
            node = level.get(key)
            if node is None:
                node = _Node(key, bid, parent, self._clock)
                level[key] = node
                self._resident += 1
                taken.append(int(bid))
            else:
                node.stamp = self._clock
                if int(bid) != node.block:
                    rejected.append(int(bid))
            level, parent = node.children, node
        return taken, rejected

    def evict(self, n_blocks):
        """Free up to ``n_blocks`` blocks, LRU-first over refcount-0
        LEAVES (peeling a cold chain bottom-up), and return their
        ids for ``PagedKVCache.reclaim``."""
        return [bid for bid, _ in self.evict_with_paths(n_blocks)]

    def evict_with_paths(self, n_blocks):
        """:meth:`evict`, but each freed block comes with the full
        token prefix it completed — ``[(block_id, path_tokens)]`` —
        so the demotion path can re-key the contents into the host
        tier before the device block is reclaimed."""
        freed = []
        while len(freed) < int(n_blocks):
            victim = None
            stack = [(None, self._root)]
            while stack:
                parent, level = stack.pop()
                for node in level.values():
                    if not node.children and not node.refs \
                            and (victim is None
                                 or node.stamp < victim.stamp):
                        victim = node
                    stack.append((node, node.children))
            if victim is None:
                break
            path = self._path_tokens(victim)
            freed.append((self._evict_node(victim), path))
        return freed

    def _evict_node(self, node):
        """Drop one node (tests poke this directly): a pinned or
        inner node is a programming error, loudly."""
        if node.refs:
            raise ValueError(
                "evicting block %d with %d live reference(s)"
                % (node.block, node.refs))
        if node.children:
            raise ValueError(
                "evicting inner block %d (%d children depend on it)"
                % (node.block, len(node.children)))
        level = self._root if node.parent is None \
            else node.parent.children
        level.pop(node.key, None)
        self._resident -= 1
        self.evictions += 1
        return node.block

    def clear(self):
        """Drop every unpinned subtree (close-time sweep); returns
        the freed block ids.  Pinned paths stay — their slots are
        still reading them."""
        freed = []
        while True:
            batch = self.evict(self._resident or 1)
            if not batch:
                return freed
            freed.extend(batch)
