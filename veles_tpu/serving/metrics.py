"""Serving metrics — per-request TTFT / tokens-per-sec, queue and
slot gauges, built on the shared :mod:`veles_tpu.telemetry` types and
wired into the JSONL event sink (:mod:`veles_tpu.logger`).

Each :class:`ServingMetrics` instance keeps its OWN counters and
latency histograms (so :meth:`snapshot` — the ``GET /serving/metrics``
JSON and the bench reader — reports this scheduler's lifetime), and
every observation is mirrored into the process-wide registry
(:data:`veles_tpu.telemetry.metrics`), where Prometheus scrapes it at
``GET /metrics`` as the cumulative ``veles_serving_*`` series.
"""

import itertools
import threading
import time
from collections import deque

from veles_tpu.logger import events
from veles_tpu.telemetry import MS_BUCKETS, Histogram, metrics, \
    nearest_rank


def _pct(sorted_vals, q):
    """Nearest-rank percentile on a sorted window (kept as the module
    helper the snapshot math uses; ``q=0.5`` over 2 elements is the
    LOWER value, ``q=0.99`` never IndexErrors on tiny windows)."""
    return nearest_rank(sorted_vals, q)


# -- SLO accounting -----------------------------------------------------------

#: priority class names (local copy — the scheduler imports this
#: module, so it cannot be imported back for its CLASS_NAMES)
_SLO_CLASSES = ("low", "normal", "high")


def _slo_conf():
    """The effective SLO config (``root.common.slo.*``): per-class
    latency objectives in ms for TTFT and whole-request (e2e) time, a
    success-ratio ``target`` whose complement is the error budget,
    and the burn-rate ``windows`` in seconds."""
    from veles_tpu.config import root
    slo = root.common.slo
    return {
        "enabled": bool(slo.get("enabled", True)),
        "target": float(slo.get("target", 0.99)),
        "windows": tuple(float(w) for w in
                         slo.get("windows", (60.0, 300.0, 3600.0))),
        "ttft_ms": {c: slo.ttft_ms.get(c, None)
                    for c in _SLO_CLASSES},
        "e2e_ms": {c: slo.e2e_ms.get(c, None)
                   for c in _SLO_CLASSES},
    }


def _slo_series():
    return {
        "good": metrics.counter(
            "veles_slo_requests_good_total",
            "requests that met their class's latency objective, by "
            "scope (serving TTFT/e2e at the replica, e2e at the "
            "router), class and objective kind",
            labelnames=("scope", "cls", "slo")),
        "bad": metrics.counter(
            "veles_slo_requests_bad_total",
            "requests that MISSED their class's latency objective — "
            "the numerator of the burn rate",
            labelnames=("scope", "cls", "slo")),
        "burn": metrics.gauge(
            "veles_slo_burn_rate",
            "error-budget burn rate over a trailing window: "
            "(bad fraction in window) / (1 - target); 1.0 burns the "
            "budget exactly at the objective rate, >1 burns faster "
            "(multi-window alerting pairs a fast and a slow window)",
            labelnames=("scope", "cls", "slo", "window")),
        "objective": metrics.gauge(
            "veles_slo_objective_ms",
            "the configured latency objective (root.common.slo.*), "
            "exported so dashboards need no config access",
            labelnames=("scope", "cls", "slo")),
    }


class SLOTracker:
    """Per-class latency-SLO accounting: good/bad counters plus
    multi-window burn-rate gauges (the SRE alerting pair), configured
    from ``root.common.slo.*`` at construction.  ``scope`` labels the
    exported series ("serving" for replica-side TTFT/e2e, "router"
    for the fleet-tail e2e clients actually see).  Thread-safe; one
    observation is a lock, a deque append and two counter bumps."""

    #: per-(cls, kind) observation window cap — at the largest
    #: default window (1 h) this bounds memory, and a saturated ring
    #: still yields a correct burn rate over the events it holds
    _RING = 4096

    def __init__(self, scope):
        conf = _slo_conf()
        self.scope = str(scope)
        self.enabled = conf["enabled"]
        self.target = conf["target"]
        self.windows = conf["windows"]
        self.objectives = {"ttft": conf["ttft_ms"],
                           "e2e": conf["e2e_ms"]}
        self._budget = max(1e-9, 1.0 - self.target)
        self._lock = threading.Lock()
        self._events = {}   # (cls, kind) -> deque[(t, bad)]
        self._good = {}
        self._bad = {}
        self._global = _slo_series()
        if self.enabled:
            for kind, by_cls in self.objectives.items():
                for cls, obj in by_cls.items():
                    if obj is not None:
                        self._global["objective"].labels(
                            scope=self.scope, cls=cls,
                            slo=kind).set(float(obj))

    def record(self, cls, kind, ms):
        """One finished observation: ``kind`` in {"ttft", "e2e"},
        ``ms`` the measured latency.  No objective configured for the
        class (or SLOs disabled) means no accounting."""
        if not self.enabled:
            return
        obj = self.objectives.get(kind, {}).get(cls)
        if obj is None:
            return
        bad = float(ms) > float(obj)
        now = time.monotonic()
        key = (cls, kind)
        with self._lock:
            ring = self._events.get(key)
            if ring is None:
                ring = self._events[key] = deque(maxlen=self._RING)
            ring.append((now, bad))
            if bad:
                self._bad[key] = self._bad.get(key, 0) + 1
            else:
                self._good[key] = self._good.get(key, 0) + 1
        self._global["bad" if bad else "good"].labels(
            scope=self.scope, cls=cls, slo=kind).inc()
        self._refresh_burn(key, now)

    def _burn_rates(self, key, now):
        """Burn rate per window from the bounded ring: bad fraction
        in the trailing window divided by the error budget."""
        with self._lock:
            ring = list(self._events.get(key, ()))
        out = {}
        for w in self.windows:
            recent = [bad for t, bad in ring if now - t <= w]
            rate = (sum(recent) / len(recent) / self._budget) \
                if recent else 0.0
            out["%ds" % int(w)] = round(rate, 4)
        return out

    def _refresh_burn(self, key, now):
        cls, kind = key
        for w, rate in zip(self.windows,
                           self._burn_rates(key, now).values()):
            self._global["burn"].labels(
                scope=self.scope, cls=cls, slo=kind,
                window="%ds" % int(w)).set(rate)

    def snapshot(self):
        """JSON view for ``/serving/metrics`` / ``/router/state`` /
        bench.py: objectives, good/bad counts and the current
        multi-window burn rates per class and kind."""
        now = time.monotonic()
        with self._lock:
            keys = list(self._events)
            good = dict(self._good)
            bad = dict(self._bad)
        out = {"enabled": self.enabled, "target": self.target,
               "windows_s": [int(w) for w in self.windows],
               "objectives_ms": {
                   k: {c: v for c, v in by.items() if v is not None}
                   for k, by in self.objectives.items()},
               "classes": {}}
        for key in keys:
            cls, kind = key
            rec = out["classes"].setdefault(cls, {})
            rec[kind] = {"good": good.get(key, 0),
                         "bad": bad.get(key, 0),
                         "burn_rate": self._burn_rates(key, now)}
            self._refresh_burn(key, now)
        return out


def _registry_series():
    return {
        "submitted": metrics.counter(
            "veles_serving_requests_submitted_total",
            "requests accepted into the serving queue"),
        "completed": metrics.counter(
            "veles_serving_requests_completed_total",
            "requests that finished decoding"),
        "rejected": metrics.counter(
            "veles_serving_requests_rejected_total",
            "requests refused at admission (queue-depth cap, HTTP 503)"),
        "expired": metrics.counter(
            "veles_serving_requests_expired_total",
            "requests that aged out while queued (HTTP 408)"),
        "tokens": metrics.counter(
            "veles_serving_tokens_generated_total",
            "tokens generated across all requests"),
        "busy_steps": metrics.counter(
            "veles_serving_slot_busy_steps_total",
            "slot-steps spent decoding an active request"),
        "total_steps": metrics.counter(
            "veles_serving_slot_steps_total",
            "slot-steps elapsed (busy + idle slots)"),
        "ttft_ms": metrics.histogram(
            "veles_serving_ttft_ms",
            "submit-to-first-token latency (ms)", buckets=MS_BUCKETS),
        "queued_ms": metrics.histogram(
            "veles_serving_queued_ms",
            "submit-to-slot-admission latency (ms)",
            buckets=MS_BUCKETS),
        "kv_blocks_used": metrics.gauge(
            "veles_serving_kv_blocks_used",
            "paged-KV blocks currently owned by in-flight requests"),
        "kv_blocks_free": metrics.gauge(
            "veles_serving_kv_blocks_free",
            "paged-KV blocks available for admission (memory-pressure"
            " rejections start when a prompt's budget exceeds this)"),
        "kv_dtype": metrics.gauge(
            "veles_serving_kv_dtype",
            "KV pool storage dtype in use (1 on the active dtype's "
            "series — fp32 is the parity baseline, int8 the "
            "quantized ~2x-streams layout); labeled per replica so "
            "a mixed fleet's schedulers stop stomping one series",
            labelnames=("dtype", "replica")),
        "kv_bytes_per_token": metrics.gauge(
            "veles_serving_kv_bytes_per_token",
            "per-chip HBM bytes one cached token costs across all "
            "layers' pools (scales included; tensor-parallel pools "
            "divide by the mesh factor) — the streams-per-HBM-"
            "dollar denominator, labeled per replica",
            labelnames=("replica",)),
        "prefill_chunks": metrics.counter(
            "veles_serving_prefill_chunk_total",
            "prompt chunks prefilled (chunked-prefill path)"),
        "prefill_chunk_tokens": metrics.counter(
            "veles_serving_prefill_chunk_tokens_total",
            "prompt tokens prefilled through the chunked path"),
        "prefill_chunk_ms": metrics.histogram(
            "veles_serving_prefill_chunk_ms",
            "wall time of one prefill chunk — the decode-stall bound "
            "each loop iteration pays for a joining long prompt",
            buckets=MS_BUCKETS),
        "cancelled": metrics.counter(
            "veles_serving_requests_cancelled_total",
            "requests cancelled mid-flight (client gone/disconnected)"
        ),
        "shed": metrics.counter(
            "veles_serving_requests_shed_total",
            "requests shed at admission under block-pressure overload"
            " (HTTP 503)"),
        "preempts": metrics.counter(
            "veles_serving_preempts_total",
            "requests evicted mid-decode (blocks released, generated "
            "prefix kept, requeued for resume)"),
        "preempt_resumes": metrics.counter(
            "veles_serving_preempt_resumes_total",
            "preempted requests re-admitted (prompt + prefix "
            "re-prefilled, stream continues bit-identically)"),
        "preempt_reprefill_tokens": metrics.counter(
            "veles_serving_preempt_reprefill_tokens_total",
            "tokens re-prefilled on resume — the compute cost "
            "preemption traded for the freed KV blocks"),
        "watchdog_trips": metrics.counter(
            "veles_serving_watchdog_trips_total",
            "decode-loop stalls detected (pending requests failed "
            "instead of hanging their clients)"),
        "drains": metrics.counter(
            "veles_serving_drains_total",
            "graceful-drain requests accepted (admission closed)"),
        "spec_drafted": metrics.counter(
            "veles_serving_spec_drafted_tokens_total",
            "tokens drafted by the speculative proposer (n-gram "
            "prompt lookup) and scored by the batched verify step"),
        "spec_accepted": metrics.counter(
            "veles_serving_spec_accepted_tokens_total",
            "drafted tokens the verify step accepted — each one a "
            "model pass the request did not pay"),
        "spec_rollback": metrics.counter(
            "veles_serving_spec_rollback_tokens_total",
            "drafted tokens rejected at verify (their KV rows are "
            "logically rolled back: masked until overwritten)"),
        "prefix_hits": metrics.counter(
            "veles_serving_prefix_hits_total",
            "admissions whose prompt prefix was resident in the "
            "radix cache (warm: only the cold tail prefilled)"),
        "prefix_misses": metrics.counter(
            "veles_serving_prefix_misses_total",
            "admissions with no resident prefix (fully cold)"),
        "prefix_hit_tokens": metrics.counter(
            "veles_serving_prefix_hit_tokens_total",
            "prompt tokens served from resident KV blocks instead "
            "of prefill compute"),
        "prefix_evictions": metrics.counter(
            "veles_serving_prefix_evicted_blocks_total",
            "resident refcount-0 blocks evicted (LRU) under "
            "admission pressure"),
        "prefix_resident": metrics.gauge(
            "veles_serving_prefix_blocks_resident",
            "KV blocks currently owned by the radix prefix cache"),
        "prefix_shared": metrics.gauge(
            "veles_serving_prefix_blocks_shared",
            "resident blocks currently pinned by at least one "
            "in-flight request"),
        # per-priority-class QoS series (low/normal/high): the
        # observable contract of preemptive scheduling — high-class
        # TTFT stays bounded BECAUSE low-class requests absorb the
        # preemptions and sheds these count
        "class_submitted": metrics.counter(
            "veles_serving_class_requests_total",
            "requests accepted into the queue, by priority class",
            labelnames=("cls",)),
        "class_completed": metrics.counter(
            "veles_serving_class_completed_total",
            "requests that finished decoding, by priority class",
            labelnames=("cls",)),
        "class_preempts": metrics.counter(
            "veles_serving_class_preempts_total",
            "mid-decode evictions, by the VICTIM's priority class",
            labelnames=("cls",)),
        "class_sheds": metrics.counter(
            "veles_serving_class_sheds_total",
            "requests shed (block pressure or a higher-class "
            "arrival taking the seat), by the SHED class",
            labelnames=("cls",)),
        "class_ttft_ms": metrics.histogram(
            "veles_serving_class_ttft_ms",
            "submit-to-first-token latency by priority class (ms)",
            labelnames=("cls",), buckets=MS_BUCKETS),
        # goodput accounting (PR 14): the decode loop already padded
        # every step to a pow2 occupancy bucket — these gauges make
        # "busy but wasting its batches" a visible, alertable fact
        "goodput": metrics.gauge(
            "veles_serving_goodput_tokens_per_sec",
            "tokens emitted per wall second over the recent "
            "decode-step window — throughput the CLIENTS received, "
            "as opposed to slot-steps burned; labeled per replica",
            labelnames=("replica",)),
        "pad_eff": metrics.gauge(
            "veles_serving_bucket_padding_efficiency",
            "real vs padded batch positions over the recent "
            "decode-step window (sum(active)/sum(bucket)); 1.0 means "
            "every padded row carried a request, low values mean the "
            "pow2 buckets are mostly padding; labeled per replica",
            labelnames=("replica",)),
        "kv_pressure": metrics.gauge(
            "veles_serving_kv_pressure",
            "paged-KV pool occupancy fraction used/(used+free) — "
            "the admission-pressure number the kv_block_pressure "
            "alert rule watches; labeled per replica",
            labelnames=("replica",)),
        "prefix_rate": metrics.gauge(
            "veles_serving_prefix_hit_rate_recent",
            "radix prefix-cache hit rate over the recent lookup "
            "window (NO sample until the window has enough lookups "
            "— an idle replica exports nothing rather than a fake "
            "healthy 1.0 that would pacify the collapse alert); "
            "labeled per replica", labelnames=("replica",)),
        # disaggregated-handoff export lifecycle: a healthy fleet
        # fetches every parked record within the TTL — pending
        # should hover near 0 and expired should never grow (the
        # kv_export_expiry alert rule watches the latter: growth
        # means the decode pool is not fetching)
        "kv_export_pending": metrics.gauge(
            "veles_serving_kv_export_pending",
            "prefill-export records parked and not yet fetched "
            "(one-shot handles awaiting the decode pool); labeled "
            "per replica", labelnames=("replica",)),
        "kv_export_expired": metrics.counter(
            "veles_serving_kv_export_expired_total",
            "export records the TTL sweeper garbage-collected "
            "unfetched — each one a decode pool that never came "
            "for its handoff; labeled per replica",
            labelnames=("replica",)),
        "kv_export_fetched": metrics.counter(
            "veles_serving_kv_export_fetched_total",
            "export records claimed by their one-shot fetch; "
            "labeled per replica", labelnames=("replica",)),
        # host-RAM KV overflow tier (serving/kv_host.py): demotions
        # park evicted prefix blocks in host RAM, promotions bring
        # them back on a matching admission.  Sustained promotion ~=
        # demotion churn means the budget is too small for the
        # working set (the kv_host_thrash alert rule)
        "kv_host_blocks": metrics.gauge(
            "veles_serving_kv_host_blocks",
            "KV blocks resident in the host-RAM overflow tier; "
            "labeled per replica", labelnames=("replica",)),
        "kv_host_bytes": metrics.gauge(
            "veles_serving_kv_host_bytes",
            "payload bytes resident in the host-RAM overflow tier "
            "(bounded by kv_host_bytes); labeled per replica",
            labelnames=("replica",)),
        "kv_host_promotions": metrics.counter(
            "veles_serving_kv_host_promotions_total",
            "host-tier blocks promoted back into device pools on a "
            "matching admission (incl. peer-prefix imports); "
            "labeled per replica", labelnames=("replica",)),
        "kv_host_demotions": metrics.counter(
            "veles_serving_kv_host_demotions_total",
            "evicted prefix blocks demoted into the host tier "
            "instead of dropped; labeled per replica",
            labelnames=("replica",)),
        "kv_host_thrash": metrics.gauge(
            "veles_serving_kv_host_thrash_rate",
            "min(promotion, demotion) blocks/s over the recent "
            "window — high when blocks ping-pong between tiers "
            "(the kv_host_thrash alert rule); labeled per replica",
            labelnames=("replica",)),
        "ttft_p95": metrics.gauge(
            "veles_serving_ttft_p95_ms",
            "recent-window TTFT p95 as a gauge (the histogram's "
            "reservoir percentile) — the series the ttft_p95_creep "
            "trend rule differentiates; labeled per replica",
            labelnames=("replica",)),
        # per-tenant cost metering (PR 17): the usage quantities a
        # bill is made of, attributed by the scheduler at step/retire
        # boundaries to the bounded tenant label (tenant/admission.py
        # first-N cardinality bound — raw ids never become label
        # values).  Counters, so the router's federated merge sums
        # them fleet-wide and the tsdb rates them over any window.
        "tenant_prompt_tokens": metrics.counter(
            "veles_tenant_usage_prompt_tokens_total",
            "prompt tokens ingested (prefill cost), by bounded "
            "tenant label", labelnames=("tenant",)),
        "tenant_generated_tokens": metrics.counter(
            "veles_tenant_usage_generated_tokens_total",
            "tokens generated (decode output), by bounded tenant "
            "label", labelnames=("tenant",)),
        "tenant_kv_block_seconds": metrics.counter(
            "veles_tenant_usage_kv_block_seconds_total",
            "KV blocks held x wall seconds, sampled at decode-step "
            "boundaries — the HBM-residency cost of a tenant's "
            "streams, by bounded tenant label",
            labelnames=("tenant",)),
        "tenant_compute_seconds": metrics.counter(
            "veles_tenant_usage_compute_seconds_total",
            "step wall time attributed to a tenant's active slots "
            "(each step's duration split evenly across its live "
            "requests), by bounded tenant label",
            labelnames=("tenant",)),
    }


# -- tenant label bounding ----------------------------------------------------

_tenant_bounder = None
_tenant_bounder_lock = threading.Lock()


def _tenant_label(tenant):
    """Bound a raw tenant id to its metrics-safe label value through
    the admission cardinality bounder (first-N distinct tenants keep
    their own label, the rest read "other") — a raw id NEVER becomes
    a label value, so a tenant flood cannot leak unbounded series
    into the registry (analysis pass M503 enforces this flow at
    every tenant-labeled registration site).  One shared bounder per
    process, so every metrics instance agrees on which N tenants won
    their own label."""
    global _tenant_bounder
    if _tenant_bounder is None:
        from veles_tpu.tenant.admission import TenantAdmission
        with _tenant_bounder_lock:
            if _tenant_bounder is None:
                _tenant_bounder = TenantAdmission()
    return _tenant_bounder.label(str(tenant or "anon"))


_BREAKER_STATES = {"closed": 0, "half_open": 1, "open": 2}


def _router_series():
    return {
        "requests": metrics.counter(
            "veles_router_requests_total",
            "forward attempts, by replica, outcome (ok/error) and "
            "bounded tenant label (first-N distinct tenants keep "
            "their own, the rest share \"other\")",
            labelnames=("replica", "outcome", "tenant")),
        "retries": metrics.counter(
            "veles_router_retries_total",
            "forward attempts retried on another replica after a "
            "failure/timeout/5xx"),
        "hedges": metrics.counter(
            "veles_router_hedges_total",
            "hedge requests launched against a straggler replica "
            "(idempotent requests only)"),
        "hedge_wins": metrics.counter(
            "veles_router_hedge_wins_total",
            "hedge requests that answered before the primary"),
        "shed": metrics.counter(
            "veles_router_shed_total",
            "requests shed at the router (503 + Retry-After: no "
            "eligible replica)"),
        "disagg": metrics.counter(
            "veles_router_disagg_handoffs_total",
            "/generate requests served disaggregated: prefill on a "
            "prefill-specialist, KV export handed to a decode "
            "replica"),
        "prefix_fetches": metrics.counter(
            "veles_router_prefix_peer_fetches_total",
            "prefix blocks shipped replica-to-replica ahead of a "
            "request (fleet-wide prefix store: export from the "
            "holder, import on the target)"),
        "prefix_fetch_fails": metrics.counter(
            "veles_router_prefix_peer_fetch_fails_total",
            "peer prefix transfers that failed or were dropped — "
            "the request still runs, just cold"),
        "breaker_state": metrics.gauge(
            "veles_router_breaker_state",
            "per-replica circuit breaker: 0 closed, 1 half-open, "
            "2 open", labelnames=("replica",)),
        "replica_up": metrics.gauge(
            "veles_router_replica_up",
            "1 while the router's health poll reaches the replica, "
            "0 once it is unreachable/out of rotation — the "
            "replica_unreachable alert rule watches this",
            labelnames=("replica",)),
        "breaker_transitions": metrics.counter(
            "veles_router_breaker_transitions_total",
            "circuit-breaker state entries, by replica and new state",
            labelnames=("replica", "to")),
        "request_ms": metrics.histogram(
            "veles_router_request_ms",
            "router-side whole-request latency (all attempts + "
            "backoff; the fleet tail clients actually see)",
            buckets=MS_BUCKETS),
        "restarts": metrics.counter(
            "veles_router_replica_restarts_total",
            "replica respawns (supervisor recovery or rolling "
            "restart)", labelnames=("replica",)),
        "drains": metrics.counter(
            "veles_router_replica_drains_total",
            "replica drains initiated through the router",
            labelnames=("replica",)),
        "streams": metrics.counter(
            "veles_router_streams_total",
            "streaming (SSE) requests PINNED to a replica — counted "
            "once per client stream (a mid-stream failover's resumed "
            "leg does NOT re-count)", labelnames=("replica",)),
        "stream_failovers": metrics.counter(
            "veles_router_stream_failovers_total",
            "mid-stream failover attempts after a pinned replica "
            "died or stalled, by outcome (resumed: the continuation "
            "spliced into the open SSE connection; failed: no "
            "eligible replica or the resume itself errored; "
            "abandoned: the client disconnected during the resume)",
            labelnames=("outcome",)),
    }


def forget_serving_replica(replica):
    """Drop every replica-labeled ``veles_serving_*`` child for one
    replica id (goodput, padding efficiency, KV pressure, export
    lifecycle, ...).  Walks the live registry rather than a fixed
    family list, so ad-hoc serving gauges a replica mirrored in sweep
    too; the label position is looked up per family, so multi-label
    families (e.g. ``{dtype, replica}``) clean up as well.
    Idempotent: families with no child for the id are untouched."""
    replica = str(replica)
    for name, fam in metrics.collect():
        if not name.startswith("veles_serving_"):
            continue
        names = getattr(fam, "labelnames", ())
        if "replica" not in names:
            continue
        idx = names.index("replica")
        for key in list(fam.children()):
            if key[idx] == replica:
                fam.remove(*key)


class RouterMetrics:
    """Thread-safe router counters, mirrored into the process-wide
    registry as the ``veles_router_*`` Prometheus families (same
    instance-plus-global split as :class:`ServingMetrics`)."""

    def __init__(self, recent=256):
        self._lock = threading.Lock()
        self.requests_ok = 0
        self.requests_error = 0
        self.retries = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.shed = 0
        self.disagg_handoffs = 0
        self.prefix_fetches = 0
        self.prefix_fetch_fails = 0
        self.restarts = 0
        self.drains = 0
        self.streams = 0
        self.stream_failovers = {}   # outcome -> count
        self._request_ms = Histogram("router_request_ms",
                                     buckets=MS_BUCKETS,
                                     reservoir=recent)
        self._global = _router_series()
        #: fleet-tail SLO: whole-request (all attempts + backoff)
        #: latency vs the per-class e2e objective — what the CLIENT
        #: experiences, as opposed to the replica-side view
        self.slo = SLOTracker("router")

    def record_forward(self, replica, ok, tenant=None):
        outcome = "ok" if ok else "error"
        with self._lock:
            if ok:
                self.requests_ok += 1
            else:
                self.requests_error += 1
        self._global["requests"].labels(
            replica=str(replica), outcome=outcome,
            tenant=str(tenant or "anon")).inc()

    def record_retry(self):
        with self._lock:
            self.retries += 1
        self._global["retries"].inc()

    def record_hedge(self):
        with self._lock:
            self.hedges += 1
        self._global["hedges"].inc()

    def record_hedge_win(self):
        with self._lock:
            self.hedge_wins += 1
        self._global["hedge_wins"].inc()

    def record_shed(self):
        with self._lock:
            self.shed += 1
        self._global["shed"].inc()
        events.record("router.shed", "single", cls="Router")

    def record_disagg(self):
        with self._lock:
            self.disagg_handoffs += 1
        self._global["disagg"].inc()

    def record_prefix_fetch(self, blocks=1):
        with self._lock:
            self.prefix_fetches += 1
        self._global["prefix_fetches"].inc(int(blocks))

    def record_prefix_fetch_fail(self):
        with self._lock:
            self.prefix_fetch_fails += 1
        self._global["prefix_fetch_fails"].inc()

    def record_breaker(self, replica, state):
        self._global["breaker_state"].labels(
            replica=str(replica)).set(_BREAKER_STATES[state])
        self._global["breaker_transitions"].labels(
            replica=str(replica), to=state).inc()
        events.record("router.breaker", "single", cls="Router",
                      replica=str(replica), to=state)

    def record_replica_up(self, replica, up):
        """Health-poll outcome: 1 reachable, 0 unreachable (the
        alert engine's replica_unreachable series)."""
        self._global["replica_up"].labels(
            replica=str(replica)).set(1 if up else 0)

    def forget_replica(self, replica):
        """Drop a deregistered replica's labeled series so a removed
        replica neither exports stale state forever nor keeps a
        resolved unreachable-alert series alive.  Router families
        first, then every ``veles_serving_*{replica=...}`` child the
        replica's own process mirrored into this registry (the
        in-process LocalReplica shape) — a retired replica must not
        leave frozen goodput/KV gauges on the exposition forever."""
        for name in ("replica_up", "breaker_state"):
            self._global[name].remove(str(replica))
        forget_serving_replica(replica)

    def record_stream(self, replica):
        with self._lock:
            self.streams += 1
        self._global["streams"].labels(replica=str(replica)).inc()

    def record_stream_failover(self, outcome):
        """One mid-stream failover attempt: ``resumed`` (the
        continuation spliced into the open SSE connection),
        ``failed`` (no eligible replica / resume errored — the
        client sees a terminal error frame) or ``abandoned`` (the
        client disconnected while the resume was in flight).  The
        resumed leg is deliberately NOT a second
        ``veles_router_streams_total`` pin — one client stream, one
        count."""
        with self._lock:
            self.stream_failovers[outcome] = \
                self.stream_failovers.get(outcome, 0) + 1
        self._global["stream_failovers"].labels(
            outcome=str(outcome)).inc()
        events.record("router.stream_failover", "single",
                      cls="Router", outcome=str(outcome))

    def record_request(self, ms, cls="normal"):
        self._request_ms.observe(ms)
        self._global["request_ms"].observe(ms)
        self.slo.record(cls, "e2e", ms)

    def record_restart(self, replica):
        with self._lock:
            self.restarts += 1
        self._global["restarts"].labels(replica=str(replica)).inc()
        events.record("router.replica_restart", "single",
                      cls="Router", replica=str(replica))

    def record_drain(self, replica):
        with self._lock:
            self.drains += 1
        self._global["drains"].labels(replica=str(replica)).inc()
        events.record("router.replica_drain", "single", cls="Router",
                      replica=str(replica))

    def snapshot(self):
        with self._lock:
            out = {
                "requests_ok": self.requests_ok,
                "requests_error": self.requests_error,
                "retries": self.retries,
                "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "shed": self.shed,
                "streams_pinned": self.streams,
                "stream_failovers": dict(self.stream_failovers),
                "prefix_peer_fetches": self.prefix_fetches,
                "prefix_peer_fetch_fails": self.prefix_fetch_fails,
                "replica_restarts": self.restarts,
                "replica_drains": self.drains,
            }
        out["request_ms_p50"] = self._request_ms.percentile(0.50)
        out["request_ms_p95"] = self._request_ms.percentile(0.95)
        out["request_ms_p99"] = self._request_ms.percentile(0.99)
        out["slo"] = self.slo.snapshot()
        return out


class ServingMetrics:
    """Thread-safe serving counters + recent-window latency stats.

    ``replica`` names this instance's series on the per-replica
    labeled gauges (``veles_serving_kv_dtype`` /
    ``kv_bytes_per_token``) — the scheduler passes its fleet
    identity; the default is a process-unique stand-in so even
    anonymous schedulers never share a label."""

    _seq = itertools.count(1)

    def __init__(self, recent=256, replica=None):
        self.replica = str(replica) if replica \
            else "serving%d" % next(self._seq)
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0       # queue-depth cap (503)
        self.expired = 0        # queue deadline (408)
        self.tokens_generated = 0
        self.slot_busy_steps = 0
        self.slot_total_steps = 0
        self.prefill_chunks = 0
        self.prefill_chunk_tokens = 0
        self.cancelled = 0      # client-gone cancellations
        self.shed = 0           # block-pressure 503s
        self.preempts = 0
        self.preempt_resumes = 0
        self.watchdog_trips = 0
        self.kv_exports_expired = 0     # TTL-swept unfetched records
        self.kv_exports_fetched = 0     # one-shot claims served
        self.spec_drafted_tokens = 0    # proposer output, cumulative
        self.spec_accepted_tokens = 0   # drafts kept at verify
        self.spec_rollback_tokens = 0   # drafts rejected at verify
        #: {drafter: [drafted, accepted]} — the arbitration between
        #: the n-gram proposer and the model draft head is per-slot,
        #: so accept rates must split by source to be interpretable
        self.spec_by_drafter = {}
        self.spec_draft_k_last = 0      # adaptive draft length, last
        self.spec_draft_k_min_seen = 0  # ...and the smallest adapted-to
        # instance-lifetime latency histograms (the shared telemetry
        # type: bounded reservoir + bucket counts), window = `recent`
        self._ttft = Histogram("ttft_ms", buckets=MS_BUCKETS,
                               reservoir=recent)
        self._queued = Histogram("queued_ms", buckets=MS_BUCKETS,
                                 reservoir=recent)
        self._completions = deque(maxlen=recent)  # (t, tokens)
        #: recent decode-step window feeding the goodput/padding
        #: gauges: (t, tokens emitted, active rows, bucket rows)
        self._steps = deque(maxlen=recent)
        #: recent prefix lookups (True = hit) for the windowed rate
        self._prefix_recent = deque(maxlen=64)
        self.kv_host_promotions = 0     # host tier -> device blocks
        self.kv_host_demotions = 0      # device -> host tier blocks
        #: recent host-tier movements feeding the thrash-rate gauge:
        #: (t, promoted, demoted)
        self._kv_host_recent = deque(maxlen=64)
        #: per-tenant usage accumulators, keyed by BOUNDED label —
        #: the scheduler-side metering ground truth the
        #: /tenants/usage fleet rollup must equal exactly:
        #: label -> {prompt_tokens, generated_tokens,
        #: kv_block_seconds, compute_seconds}
        self.tenant_usage = {}
        # per-priority-class counters + TTFT windows, created on the
        # first request of each class (most deployments see one)
        self._classes = {}
        self._t0 = time.monotonic()
        self._global = _registry_series()
        #: replica-side SLO accounting (TTFT + e2e vs the per-class
        #: objectives under root.common.slo.*)
        self.slo = SLOTracker("serving")

    def _class(self, cls):
        """The per-class accumulator dict (lock held by callers of
        the record_* methods that touch it)."""
        rec = self._classes.get(cls)
        if rec is None:
            rec = self._classes[cls] = {
                "submitted": 0, "completed": 0, "preempts": 0,
                "sheds": 0,
                "ttft": Histogram("class_ttft_ms",
                                  buckets=MS_BUCKETS, reservoir=256)}
        return rec

    # -- scheduler hooks ------------------------------------------------

    def record_submit(self, cls="normal"):
        with self._lock:
            self.submitted += 1
            self._class(cls)["submitted"] += 1
        self._global["submitted"].inc()
        self._global["class_submitted"].labels(cls=cls).inc()

    def record_reject(self, depth):
        with self._lock:
            self.rejected += 1
        self._global["rejected"].inc()
        events.record("serving.reject", "single",
                      cls="InferenceScheduler", queue_depth=depth)

    def record_expire(self, queued_ms, tokens=0, trace=None):
        """A request crossed its deadline — queued (tokens=0, the 408
        admission case) or mid-decode (tokens = generated so far)."""
        with self._lock:
            self.expired += 1
        self._global["expired"].inc()
        attrs = {"trace": trace} if trace else {}
        events.record("serving.expire", "single",
                      cls="InferenceScheduler",
                      queued_ms=round(queued_ms, 3),
                      tokens=int(tokens), **attrs)

    def record_cancel(self, tokens, trace=None):
        with self._lock:
            self.cancelled += 1
        self._global["cancelled"].inc()
        attrs = {"trace": trace} if trace else {}
        events.record("serving.cancel", "single",
                      cls="InferenceScheduler", tokens=int(tokens),
                      **attrs)

    def record_shed(self, queued_blocks, cls="normal", trace=None):
        with self._lock:
            self.shed += 1
            self.rejected += 1
            self._class(cls)["sheds"] += 1
        self._global["shed"].inc()
        self._global["rejected"].inc()
        self._global["class_sheds"].labels(cls=cls).inc()
        attrs = {"trace": trace} if trace else {}
        events.record("serving.shed", "single",
                      cls="InferenceScheduler",
                      queued_blocks=int(queued_blocks),
                      priority=cls, **attrs)

    def record_preempt(self, tokens, cls="normal", trace=None):
        with self._lock:
            self.preempts += 1
            self._class(cls)["preempts"] += 1
        self._global["preempts"].inc()
        self._global["class_preempts"].labels(cls=cls).inc()
        attrs = {"trace": trace} if trace else {}
        events.record("serving.preempt", "single",
                      cls="InferenceScheduler", tokens=int(tokens),
                      priority=cls, **attrs)

    def record_resume(self, reprefill_tokens):
        with self._lock:
            self.preempt_resumes += 1
        self._global["preempt_resumes"].inc()
        self._global["preempt_reprefill_tokens"].inc(
            int(reprefill_tokens))

    def record_watchdog_trip(self, failed, stalled_s):
        with self._lock:
            self.watchdog_trips += 1
        self._global["watchdog_trips"].inc()
        events.record("serving.watchdog_trip", "single",
                      cls="InferenceScheduler", failed=int(failed),
                      stalled_s=round(stalled_s, 3))

    def record_drain(self):
        self._global["drains"].inc()
        events.record("serving.drain", "single",
                      cls="InferenceScheduler")

    def set_kv_exports_pending(self, pending):
        self._global["kv_export_pending"].labels(
            replica=self.replica).set(int(pending))

    def record_kv_export_expired(self, n, trace=None):
        """The TTL sweeper GC'd ``n`` unfetched export records —
        growth here means the decode pool never came for its
        handoffs (the kv_export_expiry alert rule)."""
        n = int(n)
        with self._lock:
            self.kv_exports_expired += n
        self._global["kv_export_expired"].labels(
            replica=self.replica).inc(n)
        events.record("serving.kv_export_expired", "single",
                      cls="InferenceScheduler", records=n)

    def record_kv_export_fetched(self):
        with self._lock:
            self.kv_exports_fetched += 1
        self._global["kv_export_fetched"].labels(
            replica=self.replica).inc()

    def record_spec(self, drafted, accepted, drafter="ngram",
                    draft_k=None):
        """One slot's verify outcome: ``drafted`` tokens proposed,
        ``accepted`` of them kept (the correction token is free and
        not counted either way).  ``drafter`` names the source that
        proposed this slot's drafts ("ngram" or "model") so accept
        rates stay interpretable under per-slot arbitration;
        ``draft_k`` (when given) is the slot's ADAPTED draft length
        after this verify — the gauge tests watch to see the EMA
        controller shrink under rejection."""
        drafted, accepted = int(drafted), int(accepted)
        with self._lock:
            self.spec_drafted_tokens += drafted
            self.spec_accepted_tokens += accepted
            self.spec_rollback_tokens += drafted - accepted
            rec = self.spec_by_drafter.setdefault(str(drafter), [0, 0])
            rec[0] += drafted
            rec[1] += accepted
            if draft_k is not None:
                draft_k = int(draft_k)
                self.spec_draft_k_last = draft_k
                if not self.spec_draft_k_min_seen \
                        or draft_k < self.spec_draft_k_min_seen:
                    self.spec_draft_k_min_seen = draft_k
        self._global["spec_drafted"].inc(drafted)
        self._global["spec_accepted"].inc(accepted)
        self._global["spec_rollback"].inc(drafted - accepted)

    # -- per-tenant metering (PR 17) ------------------------------------

    def _tenant_rec(self, label):
        """lock held."""
        rec = self.tenant_usage.get(label)
        if rec is None:
            rec = self.tenant_usage[label] = {
                "prompt_tokens": 0, "generated_tokens": 0,
                "kv_block_seconds": 0.0, "compute_seconds": 0.0}
        return rec

    def record_tenant_tokens(self, tenant, prompt=0, generated=0):
        """Retire-time token attribution (failed requests attribute
        too — the prefill/decode compute was spent either way)."""
        label = _tenant_label(tenant)
        prompt, generated = int(prompt), int(generated)
        with self._lock:
            rec = self._tenant_rec(label)
            rec["prompt_tokens"] += prompt
            rec["generated_tokens"] += generated
        if prompt:
            self._global["tenant_prompt_tokens"].labels(
                tenant=label).inc(prompt)
        if generated:
            self._global["tenant_generated_tokens"].labels(
                tenant=label).inc(generated)

    def record_tenant_step(self, usage):
        """One decode-step boundary's residency/compute attribution:
        ``usage`` maps raw tenant id ->
        ``(kv_block_seconds, compute_seconds)`` increments the
        scheduler sampled for that step (blocks held x step wall
        time; the step's duration split across its active slots)."""
        for tenant, (blocks_s, compute_s) in usage.items():
            label = _tenant_label(tenant)
            with self._lock:
                rec = self._tenant_rec(label)
                rec["kv_block_seconds"] += blocks_s
                rec["compute_seconds"] += compute_s
            if blocks_s > 0:
                self._global["tenant_kv_block_seconds"].labels(
                    tenant=label).inc(blocks_s)
            if compute_s > 0:
                self._global["tenant_compute_seconds"].labels(
                    tenant=label).inc(compute_s)

    def tenant_usage_snapshot(self):
        """Per-tenant usage rollup (bounded labels), rounded for the
        JSON surface."""
        with self._lock:
            return {label: {
                "prompt_tokens": rec["prompt_tokens"],
                "generated_tokens": rec["generated_tokens"],
                "kv_block_seconds": round(rec["kv_block_seconds"], 6),
                "compute_seconds": round(rec["compute_seconds"], 6),
            } for label, rec in sorted(self.tenant_usage.items())}

    #: minimum recent lookups before the windowed hit rate is
    #: trusted — below it NO sample is exported (the series is
    #: absent, not a fake-healthy 1.0), so the prefix_hit_collapse
    #: alert neither fires on idle/startup traffic nor gets
    #: pacified by an idle replica's placeholder
    _PREFIX_MIN_LOOKUPS = 16

    def record_prefix_lookup(self, matched_blocks, block_size):
        """One admission's radix-cache lookup: a hit when >= 1
        leading block was resident."""
        if matched_blocks > 0:
            self._global["prefix_hits"].inc()
            self._global["prefix_hit_tokens"].inc(
                int(matched_blocks) * int(block_size))
        else:
            self._global["prefix_misses"].inc()
        with self._lock:
            self._prefix_recent.append(matched_blocks > 0)
            window = list(self._prefix_recent)
        if len(window) < self._PREFIX_MIN_LOOKUPS:
            self._global["prefix_rate"].remove(self.replica)
            return
        rate = sum(window) / len(window)
        self._global["prefix_rate"].labels(
            replica=self.replica).set(round(rate, 4))

    def record_prefix_evict(self, blocks):
        self._global["prefix_evictions"].inc(int(blocks))

    def record_kv_host(self, promoted=0, demoted=0):
        """Host-tier block movement at one boundary; also refreshes
        the thrash-rate gauge — min(promotion, demotion) blocks/s
        over the recent window, which is high exactly when the same
        blocks ping-pong between tiers (budget too small for the
        working set) and near zero for healthy one-way flow."""
        promoted, demoted = int(promoted), int(demoted)
        now = time.monotonic()
        with self._lock:
            self.kv_host_promotions += promoted
            self.kv_host_demotions += demoted
            self._kv_host_recent.append((now, promoted, demoted))
            window = list(self._kv_host_recent)
        if promoted:
            self._global["kv_host_promotions"].labels(
                replica=self.replica).inc(promoted)
        if demoted:
            self._global["kv_host_demotions"].labels(
                replica=self.replica).inc(demoted)
        span = now - window[0][0]
        if span <= 0 or len(window) < 2:
            return
        rate = min(sum(w[1] for w in window),
                   sum(w[2] for w in window)) / span
        self._global["kv_host_thrash"].labels(
            replica=self.replica).set(round(rate, 4))

    def set_kv_host(self, blocks, nbytes):
        self._global["kv_host_blocks"].labels(
            replica=self.replica).set(int(blocks))
        self._global["kv_host_bytes"].labels(
            replica=self.replica).set(int(nbytes))

    def set_prefix_blocks(self, resident, shared):
        self._global["prefix_resident"].set(int(resident))
        self._global["prefix_shared"].set(int(shared))

    def record_first_token(self, ttft_ms, queued_ms, cls="normal"):
        self._ttft.observe(ttft_ms)
        self._queued.observe(queued_ms)
        with self._lock:
            self._class(cls)["ttft"].observe(ttft_ms)
        self._global["ttft_ms"].observe(ttft_ms)
        self._global["queued_ms"].observe(queued_ms)
        self._global["class_ttft_ms"].labels(cls=cls).observe(ttft_ms)
        self._global["ttft_p95"].labels(replica=self.replica).set(
            round(self._ttft.percentile(0.95), 3))
        self.slo.record(cls, "ttft", ttft_ms)

    def record_prefill_chunk(self, tokens, chunk_ms):
        with self._lock:
            self.prefill_chunks += 1
            self.prefill_chunk_tokens += int(tokens)
        self._global["prefill_chunks"].inc()
        self._global["prefill_chunk_tokens"].inc(int(tokens))
        self._global["prefill_chunk_ms"].observe(chunk_ms)

    def set_kv_blocks(self, used, free):
        self._global["kv_blocks_used"].set(int(used))
        self._global["kv_blocks_free"].set(int(free))
        total = int(used) + int(free)
        self._global["kv_pressure"].labels(replica=self.replica).set(
            round(int(used) / total, 4) if total else 0.0)

    def set_kv_dtype(self, kv_dtype, bytes_per_token):
        """Advertise the KV pool layout (once, at cache build): the
        active dtype's labeled series reads 1, the other 0 — a
        dashboard can tell at a glance which fleet replicas run
        quantized pools and what a cached token costs them.  Both
        gauges carry this instance's ``replica`` label, so a
        multi-replica fleet (or a test building several schedulers
        in one process) no longer last-writer-wins one shared
        series."""
        for d in ("fp32", "int8"):
            self._global["kv_dtype"].labels(
                dtype=d, replica=self.replica).set(
                1 if d == kv_dtype else 0)
        self._global["kv_bytes_per_token"].labels(
            replica=self.replica).set(int(bytes_per_token))

    def record_step(self, active, slots, tokens=None,
                    duration_s=None):
        """One batched decode/verify boundary: ``active`` real rows
        rode a padded ``slots``-row bucket; ``tokens`` is what the
        step actually emitted (spec verify can emit up to k+1 per
        slot, a fully-rejected slot emits 0) and feeds the goodput
        gauge; ``duration_s`` is accepted for symmetry with the
        tracing hook (the goodput window uses wall-clock arrival
        times, so a stalled loop DROPS the gauge instead of freezing
        it at the last healthy rate)."""
        now = time.monotonic()
        with self._lock:
            self.slot_busy_steps += int(active)
            self.slot_total_steps += int(slots)
            if tokens is not None:
                self._steps.append((now, int(tokens), int(active),
                                    int(slots)))
                window = list(self._steps)
            else:
                window = None
        self._global["busy_steps"].inc(int(active))
        self._global["total_steps"].inc(int(slots))
        if not window:
            return
        pad = sum(s for _, _, _, s in window)
        eff = sum(a for _, _, a, _ in window) / pad if pad else 0.0
        self._global["pad_eff"].labels(replica=self.replica).set(
            round(eff, 4))
        span = window[-1][0] - window[0][0]
        if len(window) >= 2 and span > 0:
            tps = sum(t for _, t, _, _ in window) / span
            self._global["goodput"].labels(
                replica=self.replica).set(round(tps, 2))

    def goodput_snapshot(self):
        """(tokens_per_sec, padding_efficiency) over the recent step
        window — the /serving/metrics + bench read."""
        with self._lock:
            window = list(self._steps)
        if not window:
            return None, None
        pad = sum(s for _, _, _, s in window)
        eff = round(sum(a for _, _, a, _ in window) / pad, 4) \
            if pad else None
        span = window[-1][0] - window[0][0]
        tps = round(sum(t for _, t, _, _ in window) / span, 2) \
            if len(window) >= 2 and span > 0 else None
        return tps, eff

    def record_complete(self, req_tokens, duration_s, ttft_ms,
                        queued_ms, cls="normal", trace=None):
        now = time.monotonic()
        with self._lock:
            self.completed += 1
            self.tokens_generated += int(req_tokens)
            self._completions.append((now, int(req_tokens)))
            self._class(cls)["completed"] += 1
        self._global["completed"].inc()
        self._global["tokens"].inc(int(req_tokens))
        self._global["class_completed"].labels(cls=cls).inc()
        self.slo.record(cls, "e2e", duration_s * 1e3)
        attrs = {"trace": trace} if trace else {}
        events.record(
            "serving.request", "single", cls="InferenceScheduler",
            tokens=int(req_tokens), ttft_ms=round(ttft_ms, 3),
            queued_ms=round(queued_ms, 3),
            duration_ms=round(duration_s * 1e3, 3),
            tokens_per_sec=round(req_tokens / duration_s, 1)
            if duration_s > 0 else None, **attrs)

    # -- reads ----------------------------------------------------------

    def recent_tokens_per_sec(self):
        """Aggregate decode throughput over the recent completion
        window (None before two completions)."""
        with self._lock:
            if len(self._completions) < 2:
                return None
            t_first = self._completions[0][0]
            t_last = self._completions[-1][0]
            toks = sum(n for _, n in self._completions)
            if t_last <= t_first:
                return None
            return toks / (t_last - t_first)

    def snapshot(self, queue_depth=0, active_slots=0, max_slots=0,
                 kv=None):
        with self._lock:
            occ = (self.slot_busy_steps / self.slot_total_steps
                   if self.slot_total_steps else 0.0)
            out = {
                "requests_submitted": self.submitted,
                "requests_completed": self.completed,
                "requests_rejected": self.rejected,
                "requests_expired": self.expired,
                "tokens_generated": self.tokens_generated,
                "queue_depth": int(queue_depth),
                "active_slots": int(active_slots),
                "max_slots": int(max_slots),
                "slot_occupancy": round(occ, 4),
                "slot_busy_steps": self.slot_busy_steps,
                "prefill_chunks": self.prefill_chunks,
                "prefill_chunk_tokens": self.prefill_chunk_tokens,
                "requests_cancelled": self.cancelled,
                "requests_shed": self.shed,
                "preempts": self.preempts,
                "preempt_resumes": self.preempt_resumes,
                "watchdog_trips": self.watchdog_trips,
                "kv_exports_expired": self.kv_exports_expired,
                "kv_exports_fetched": self.kv_exports_fetched,
                "spec_drafted_tokens": self.spec_drafted_tokens,
                "spec_accepted_tokens": self.spec_accepted_tokens,
                "spec_rollback_tokens": self.spec_rollback_tokens,
                "spec_accept_rate": round(
                    self.spec_accepted_tokens
                    / self.spec_drafted_tokens, 4)
                if self.spec_drafted_tokens else None,
                "spec_accept_rate_by_drafter": {
                    name: round(rec[1] / rec[0], 4) if rec[0] else None
                    for name, rec in sorted(
                        self.spec_by_drafter.items())},
                "spec_draft_k_last": self.spec_draft_k_last,
                "spec_draft_k_min_seen": self.spec_draft_k_min_seen,
                "uptime_s": round(time.monotonic() - self._t0, 3),
            }
        if kv:  # paged-cache occupancy (operator admission headroom)
            out.update(kv)
        with self._lock:
            out["classes"] = {
                cls: {"submitted": rec["submitted"],
                      "completed": rec["completed"],
                      "preempts": rec["preempts"],
                      "sheds": rec["sheds"],
                      "ttft_ms_p50": rec["ttft"].percentile(0.50),
                      "ttft_ms_p95": rec["ttft"].percentile(0.95)}
                for cls, rec in self._classes.items()}
        out["ttft_ms_p50"] = self._ttft.percentile(0.50)
        out["ttft_ms_p95"] = self._ttft.percentile(0.95)
        out["ttft_ms_p99"] = self._ttft.percentile(0.99)
        out["queued_ms_p50"] = self._queued.percentile(0.50)
        tps = self.recent_tokens_per_sec()
        out["tokens_per_sec_recent"] = round(tps, 1) if tps else None
        goodput, pad_eff = self.goodput_snapshot()
        out["goodput_tokens_per_sec"] = goodput
        out["bucket_padding_efficiency"] = pad_eff
        out["slo"] = self.slo.snapshot()
        return out
