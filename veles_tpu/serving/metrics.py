"""Serving metrics — per-request TTFT / tokens-per-sec, queue and
slot gauges, wired into the JSONL event sink (:mod:`veles_tpu.logger`)
the L8 status plumbing already ships to the web dashboard.

The scheduler calls the ``record_*`` hooks; :meth:`snapshot` returns
the aggregate dict REST exposes at ``GET /serving/metrics`` (and
``bench.py`` reads for the serving entries).
"""

import threading
import time
from collections import deque

from veles_tpu.logger import events


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(len(sorted_vals) * q))
    return sorted_vals[i]


class ServingMetrics:
    """Thread-safe serving counters + recent-window latency stats."""

    def __init__(self, recent=256):
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0       # queue-depth cap (503)
        self.expired = 0        # queue deadline (408)
        self.tokens_generated = 0
        self.slot_busy_steps = 0
        self.slot_total_steps = 0
        # recent windows for percentile / throughput reads
        self._ttft_ms = deque(maxlen=recent)
        self._queued_ms = deque(maxlen=recent)
        self._completions = deque(maxlen=recent)  # (t, tokens)
        self._t0 = time.monotonic()

    # -- scheduler hooks ------------------------------------------------

    def record_submit(self):
        with self._lock:
            self.submitted += 1

    def record_reject(self, depth):
        with self._lock:
            self.rejected += 1
        events.record("serving.reject", "single",
                      cls="InferenceScheduler", queue_depth=depth)

    def record_expire(self, queued_ms):
        with self._lock:
            self.expired += 1
        events.record("serving.expire", "single",
                      cls="InferenceScheduler",
                      queued_ms=round(queued_ms, 3))

    def record_first_token(self, ttft_ms, queued_ms):
        with self._lock:
            self._ttft_ms.append(float(ttft_ms))
            self._queued_ms.append(float(queued_ms))

    def record_step(self, active, slots):
        with self._lock:
            self.slot_busy_steps += int(active)
            self.slot_total_steps += int(slots)

    def record_complete(self, req_tokens, duration_s, ttft_ms,
                        queued_ms):
        now = time.monotonic()
        with self._lock:
            self.completed += 1
            self.tokens_generated += int(req_tokens)
            self._completions.append((now, int(req_tokens)))
        events.record(
            "serving.request", "single", cls="InferenceScheduler",
            tokens=int(req_tokens), ttft_ms=round(ttft_ms, 3),
            queued_ms=round(queued_ms, 3),
            duration_ms=round(duration_s * 1e3, 3),
            tokens_per_sec=round(req_tokens / duration_s, 1)
            if duration_s > 0 else None)

    # -- reads ----------------------------------------------------------

    def recent_tokens_per_sec(self):
        """Aggregate decode throughput over the recent completion
        window (None before two completions)."""
        with self._lock:
            if len(self._completions) < 2:
                return None
            t_first = self._completions[0][0]
            t_last = self._completions[-1][0]
            toks = sum(n for _, n in self._completions)
            if t_last <= t_first:
                return None
            return toks / (t_last - t_first)

    def snapshot(self, queue_depth=0, active_slots=0, max_slots=0):
        with self._lock:
            ttft = sorted(self._ttft_ms)
            queued = sorted(self._queued_ms)
            occ = (self.slot_busy_steps / self.slot_total_steps
                   if self.slot_total_steps else 0.0)
            out = {
                "requests_submitted": self.submitted,
                "requests_completed": self.completed,
                "requests_rejected": self.rejected,
                "requests_expired": self.expired,
                "tokens_generated": self.tokens_generated,
                "queue_depth": int(queue_depth),
                "active_slots": int(active_slots),
                "max_slots": int(max_slots),
                "slot_occupancy": round(occ, 4),
                "ttft_ms_p50": _pct(ttft, 0.50),
                "ttft_ms_p95": _pct(ttft, 0.95),
                "queued_ms_p50": _pct(queued, 0.50),
                "uptime_s": round(time.monotonic() - self._t0, 3),
            }
        tps = self.recent_tokens_per_sec()
        out["tokens_per_sec_recent"] = round(tps, 1) if tps else None
        return out
