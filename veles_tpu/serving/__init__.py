"""Continuous-batching inference serving (Orca/vLLM lineage), built
natively on the jitted decode machinery in ``models/generate``.

The decode path this package replaces served one client at a time:
REST ``/generate`` held a single decode lock and prompt prefill was a
per-token scan.  Here:

- :mod:`veles_tpu.serving.prefill` — batched prefill: ONE jitted
  forward over the whole prompt fills the KV cache (TTFT O(1)
  compiled steps instead of O(prompt_len));
- :mod:`veles_tpu.serving.kv_slots` — a slot-based batched KV cache
  (fixed ``max_slots × window`` buffers, per-slot lengths) so requests
  at different decode positions share one compiled step;
- :mod:`veles_tpu.serving.engine` — that shared compiled step:
  per-slot positions, per-slot sampler settings, per-request PRNG
  streams;
- :mod:`veles_tpu.serving.scheduler` — the continuous-batching
  scheduler: requests join free slots at token boundaries and leave
  on stop-token/step-limit, with admission control (queue-depth cap →
  503, queue deadline → 408) and a background decode loop;
- :mod:`veles_tpu.serving.metrics` — per-request TTFT, tokens/sec,
  queue depth and slot occupancy, exposed through the JSONL event
  sink (:mod:`veles_tpu.logger`) and a ``snapshot()`` dict.
"""

from veles_tpu.serving.engine import slot_decode_step  # noqa: F401
from veles_tpu.serving.kv_slots import SlotKVCache  # noqa: F401
from veles_tpu.serving.metrics import ServingMetrics  # noqa: F401
from veles_tpu.serving.prefill import (  # noqa: F401
    prefill, serving_supported)
from veles_tpu.serving.scheduler import (  # noqa: F401
    DeadlineExceededError, InferenceScheduler, QueueFullError,
    SchedulerError)
