"""Continuous-batching inference serving (Orca/vLLM lineage), built
natively on the jitted decode machinery in ``models/generate``.

The decode path this package replaces served one client at a time:
REST ``/generate`` held a single decode lock and prompt prefill was a
per-token scan.  Here:

- :mod:`veles_tpu.serving.prefill` — batched prefill: ONE jitted
  forward over the whole prompt fills the KV cache (TTFT O(1)
  compiled steps instead of O(prompt_len)), and CHUNKED prefill
  (:func:`prefill_chunk`) splits long prompts into fixed-size chunks
  the scheduler interleaves with decode steps (Sarathi-style) so a
  joining long prompt cannot stall in-flight streams;
- :mod:`veles_tpu.serving.kv_slots` — the KV caches: the default
  block-PAGED cache (:class:`PagedKVCache` — vLLM PagedAttention
  lineage: per-layer block pools + per-slot block tables, so memory
  scales with each request's actual length and admission is
  memory-proportional) and the legacy dense :class:`SlotKVCache`
  (fixed ``max_slots × window`` rows — the parity baseline);
- :mod:`veles_tpu.serving.engine` — the shared compiled decode
  steps: per-slot positions, per-slot sampler settings, per-request
  PRNG streams; the paged step packs only the active slots into
  power-of-two occupancy buckets and bounds attention by a block
  bucket over the deepest request;
- :mod:`veles_tpu.serving.scheduler` — the continuous-batching
  scheduler: requests join free slots (and, paged, claim their block
  budget) at token boundaries and leave on stop-token/step-limit,
  with admission control (queue-depth cap → 503, queue deadline →
  408) and a background decode loop;
- :mod:`veles_tpu.serving.metrics` — per-request TTFT, tokens/sec,
  queue depth, slot occupancy, KV-block occupancy and prefill-chunk
  stalls, exposed through the JSONL event sink
  (:mod:`veles_tpu.logger`) and a ``snapshot()`` dict;
- :mod:`veles_tpu.serving.router` — the multi-replica fleet tier: a
  health-aware asyncio HTTP router (least-outstanding routing with
  prefix/session affinity, per-replica circuit breakers, deadline-
  bounded retries with capped backoff, bounded hedging for
  idempotent requests, fleet-level load shedding) over N engine
  replicas;
- :mod:`veles_tpu.serving.fleet` — replica supervision: spawn N
  replicas (in-process or subprocess handles), respawn the dead, and
  orchestrate zero-downtime rolling restarts (drain → restart →
  re-admit) through the router;
- :mod:`veles_tpu.serving.spec` — speculative decoding: the n-gram
  prompt-lookup draft proposer whose k drafts the batched verify
  step (``engine.verify_step_paged``) scores in ONE model pass —
  accepted prefixes are pure latency win, output streams stay
  bit-identical to spec-off decoding;
- :mod:`veles_tpu.serving.draft` — MODEL-based drafting past the
  n-gram ceiling: Medusa-style per-position heads over the target's
  final hidden state (the engine's ``want_hidden`` lane), trained
  against the frozen target, arbitrated per slot against the free
  n-gram proposer by accept-rate EMA — which also adapts each
  slot's draft length along the warmed verify width buckets;
- :mod:`veles_tpu.serving.prefix_cache` — the cross-request radix
  prefix cache (SGLang lineage) over the paged block pools: finished
  requests donate their KV blocks, warm prompts skip prefill for
  every resident leading block and claim only their cold tail's
  budget;
- :mod:`veles_tpu.serving.streams` — per-request incremental token
  delivery: ``submit(..., stream=True)`` returns a
  :class:`TokenStream` the decode loop pushes accepted tokens into
  (SSE surfaces on REST and the router proxies them chunk by chunk);
- :mod:`veles_tpu.serving.openai_api` — the OpenAI-compatible facade
  (``/v1/completions`` with streaming + usage, ``/v1/models``) and
  the servable non-LM endpoints (batched ``/v1/embeddings`` pooled
  hidden states, ``/v1/classify`` last-position class scores), both
  executed on the decode loop's aux lane;
- :mod:`veles_tpu.serving.tp` — tensor-parallel serving: the jitted
  steps shard over a ``{"tp": N}`` mesh (Megatron column/row weight
  splits, HEAD-WISE paged pools — per-chip ``kv_blocks`` HBM drops
  by the mesh factor) while every host-side structure stays
  replicated, so a model too wide for one chip still serves with
  tp=1-bit-identical greedy streams;
- :mod:`veles_tpu.serving.disagg` — disaggregated prefill/decode
  (DistServe lineage): prefill-role replicas export finished KV
  blocks raw (scales riding along) under a handle, decode-role
  replicas import them and run only the token loop, and the router
  dispatches by role — handoff streams identical to colocated.
"""

from veles_tpu.serving.engine import (  # noqa: F401
    hidden_supported, overlap_supported, paged_decode_step,
    slot_decode_step, verify_step_paged, verify_supported)
from veles_tpu.serving.kv_slots import (  # noqa: F401
    PagedKVCache, SlotKVCache, paged_supported)
from veles_tpu.serving.prefix_cache import (  # noqa: F401
    RadixPrefixCache)
from veles_tpu.serving.spec import (  # noqa: F401
    NgramIndex, NgramProposer, accept_drafts)
from veles_tpu.serving.draft import (  # noqa: F401
    MedusaDraftHead, draft_supported)
from veles_tpu.serving.kv_quality import (  # noqa: F401
    kv_quant_quality, weight_quant_quality)
from veles_tpu.serving.metrics import (  # noqa: F401
    RouterMetrics, ServingMetrics)
from veles_tpu.serving.prefill import (  # noqa: F401
    chunked_supported, prefill, prefill_chunk, serving_supported)
from veles_tpu.serving.fleet import (  # noqa: F401
    Fleet, LocalReplica, SubprocessReplica, free_port)
from veles_tpu.serving.router import Router  # noqa: F401
from veles_tpu.serving.scheduler import (  # noqa: F401
    CLASS_NAMES, DeadlineExceededError, DrainingError,
    InferenceScheduler, PRIORITIES, QueueFullError,
    RequestCancelledError, RoleMismatchError, SchedulerError,
    resolve_priority)
from veles_tpu.serving.tp import (  # noqa: F401
    ServingTP, per_chip_bytes, tp_allreduce, tp_supported)
from veles_tpu.serving.disagg import (  # noqa: F401
    decode_export, encode_export)
from veles_tpu.serving.streams import (  # noqa: F401
    SSE_DONE, StreamTimeoutError, TokenStream, sse_event)
from veles_tpu.serving import openai_api  # noqa: F401
