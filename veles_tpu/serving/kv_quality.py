"""Quality gate for the quantized KV cache.

int8 pools trade per-element precision for ~2x streams per HBM
budget; that trade must be MEASURED, not asserted.  This module
teacher-forces a sequence through the REAL paged verify path —
block-size-wide :meth:`apply_verify_paged` passes, so every key a
position attends over was quantized when its block was written,
exactly the cache state live decode reads — once over fp32 pools and
once over int8, and reports:

- ``ce_fp32`` / ``ce_int8`` / ``ce_delta`` — mean next-token
  cross-entropy (nats) under each pool dtype, and the int8 penalty;
- ``top1_agreement`` — the fraction of positions whose greedy argmax
  matches between the two runs (what a greedy client would notice);
- ``within_tolerance`` — ``ce_delta <= KV_QUANT_CE_TOLERANCE``, the
  bound tier-1 asserts (tests/test_kv_quant.py) and ``quality.py``
  records, which is what gates flipping ``kv_dtype`` on a fleet.

The harness drives the chain eagerly (no jit) — it is a measurement
rig, not a serving path; ``quality.py`` runs it on the trained tiny
chain and merges the record into the quality JSON.
"""

import numpy

import jax.numpy as jnp

#: declared int8-KV quality bound, in nats of mean next-token CE
#: delta vs fp32 pools on the quality chains.  Per-row absmax int8
#: keeps K/V within amax/254 per element; on the trained tiny chain
#: the measured delta sits well under 0.02 — the bound leaves margin
#: without ever excusing a broken quant path (a scale bug costs
#: whole nats)
KV_QUANT_CE_TOLERANCE = 0.05

#: declared int8-WEIGHT quality bound (quantize_weights — per
#: output-column absmax, deferred dequant on the f32 accumulator),
#: same units and same reasoning as the KV bound: measured deltas on
#: the trained tiny chain sit far below it, a scale bug blows
#: through it
WEIGHT_QUANT_CE_TOLERANCE = 0.05


def _verify_pass(forwards, params, toks, pos, lens, tables, pools):
    """One teacher-forced chunk through the chain's verify path —
    the same unit dispatch as ``engine._make_verify_step``, returning
    logits instead of samples."""
    h = jnp.asarray(toks, jnp.int32)
    out = dict(pools)
    for i, u in enumerate(forwards):
        if hasattr(u, "init_cache"):
            h, out[i] = u.apply_verify_paged(params[i], h, pos, lens,
                                             tables, out[i])
        elif hasattr(u, "apply_verify_slots"):
            h = u.apply_verify_slots(params[i], h, pos)
        else:
            h = u.apply(params[i], h)
    return numpy.asarray(h.astype(jnp.float32)), out


def teacher_forced_logits(forwards, seq, block_size=16,
                          kv_dtype="fp32"):
    """Per-position next-token logits of ``seq`` through the paged
    verify path over ``kv_dtype`` pools, fed ``block_size`` tokens
    per pass (the spec-verify width regime: keys within a pass are
    written this pass, everything earlier reads back through the
    pool — quantized when int8).  Returns [L, vocab] f32 where row j
    predicts ``seq[j + 1]`` (L = the whole-block prefix length)."""
    from veles_tpu import dtypes
    from veles_tpu.models.generate import _device_params
    params = _device_params(forwards)
    bs = int(block_size)
    n_blocks = len(seq) // bs
    if n_blocks < 1:
        raise ValueError("sequence shorter than one block")
    pools = {}
    for i, u in enumerate(forwards):
        if not hasattr(u, "init_cache"):
            continue
        if not hasattr(u, "init_block_pool"):
            raise ValueError("%s has no init_block_pool"
                             % type(u).__name__)
        pools[i] = u.init_block_pool(n_blocks + 1, bs,
                                     dtypes.compute_dtype(),
                                     kv_dtype=kv_dtype)
    tables = jnp.asarray(
        numpy.arange(1, n_blocks + 1, dtype=numpy.int32)[None, :])
    lens = jnp.asarray([bs], jnp.int32)
    rows = []
    for t in range(n_blocks):
        chunk = numpy.asarray(seq[t * bs:(t + 1) * bs],
                              numpy.int32)[None, :]
        pos = jnp.asarray([t * bs], jnp.int32)
        logits, pools = _verify_pass(forwards, params, chunk, pos,
                                     lens, tables, pools)
        rows.append(logits[0])
    return numpy.concatenate(rows, axis=0)


def _mean_ce(logits, targets):
    z = logits - logits.max(axis=-1, keepdims=True)
    logp = z - numpy.log(numpy.exp(z).sum(axis=-1, keepdims=True))
    return float(-logp[numpy.arange(len(targets)), targets].mean())


def kv_quant_quality(forwards, seqs, block_size=16,
                     tolerance=KV_QUANT_CE_TOLERANCE):
    """Measure the int8-KV quality cost on ``seqs`` (token lists):
    teacher-forced CE + greedy top-1 agreement, fp32 pools vs int8,
    through the identical verify path.  Returns the record quality.py
    stores and tier-1 asserts on."""
    ce_fp, ce_q8, agree, total = [], [], 0, 0
    for seq in seqs:
        lf = teacher_forced_logits(forwards, seq, block_size, "fp32")
        lq = teacher_forced_logits(forwards, seq, block_size, "int8")
        n = min(len(lf), len(seq) - 1)   # row j predicts seq[j + 1]
        targets = numpy.asarray(seq[1:n + 1], numpy.intp)
        ce_fp.append(_mean_ce(lf[:n], targets))
        ce_q8.append(_mean_ce(lq[:n], targets))
        agree += int((lf[:n].argmax(-1) == lq[:n].argmax(-1)).sum())
        total += n
    ce_fp32 = float(numpy.mean(ce_fp))
    ce_int8 = float(numpy.mean(ce_q8))
    delta = ce_int8 - ce_fp32
    return {
        "kv_quant_ce_fp32": round(ce_fp32, 6),
        "kv_quant_ce_int8": round(ce_int8, 6),
        "kv_quant_ce_delta": round(delta, 6),
        "kv_quant_top1_agreement": round(agree / total, 6)
        if total else None,
        "kv_quant_ce_tolerance": tolerance,
        "kv_quant_within_tolerance": bool(delta <= tolerance),
        "kv_quant_positions": total,
        "kv_quant_block_size": int(block_size),
    }


def weight_quant_quality(forwards, seqs, block_size=16,
                         tolerance=WEIGHT_QUANT_CE_TOLERANCE):
    """Measure the int8 CHECKPOINT-weight quality cost (the
    ``weights_dtype="int8"`` snapshot load / ``quantize_weights``
    path) the same way ``kv_quant_quality`` measures KV: teacher-
    forced CE through the identical verify path, f32 weights first,
    then AFTER quantizing every block in place.  NOTE: the chain
    comes back quantized — run this gate last (or on a throwaway
    load), exactly how quality.py and the tp bench use it."""
    ce_fp, total_targets = [], []
    for seq in seqs:
        lf = teacher_forced_logits(forwards, seq, block_size, "fp32")
        n = min(len(lf), len(seq) - 1)
        targets = numpy.asarray(seq[1:n + 1], numpy.intp)
        ce_fp.append(_mean_ce(lf[:n], targets))
        total_targets.append((n, targets))
    quantized = 0
    for u in forwards:
        if hasattr(u, "quantize_weights"):
            u.quantize_weights()
            quantized += 1
    if not quantized:
        raise ValueError("no quantizable unit in the chain")
    ce_q8, agree, total = [], 0, 0
    for seq, (n, targets) in zip(seqs, total_targets):
        lf = teacher_forced_logits(forwards, seq, block_size, "fp32")
        lq = lf[:n]
        ce_q8.append(_mean_ce(lq, targets))
        total += n
    ce_fp32 = float(numpy.mean(ce_fp))
    ce_int8 = float(numpy.mean(ce_q8))
    delta = ce_int8 - ce_fp32
    return {
        "weight_quant_ce_fp32": round(ce_fp32, 6),
        "weight_quant_ce_int8": round(ce_int8, 6),
        "weight_quant_ce_delta": round(delta, 6),
        "weight_quant_ce_tolerance": tolerance,
        "weight_quant_within_tolerance": bool(delta <= tolerance),
        "weight_quant_positions": total,
        "weight_quant_blocks": quantized,
    }
