"""OpenAI-compatible facade + the servable non-LM entry points.

Ecosystem clients (SDKs, gateways, load-test harnesses) speak the
OpenAI REST dialect; this module maps it onto the Veles serving
engine so the fleet is a drop-in backend:

- ``POST /v1/completions`` — prompt in, completion out, with
  ``stream: true`` SSE chunks and ``usage`` accounting.  The engine
  is tokenizer-free (clients send token ids), so the ``text`` field
  of every choice carries SPACE-SEPARATED DECIMAL TOKEN IDS and the
  non-standard ``tokens`` field carries them as ints — deterministic
  and machine-parseable, which is what a drop-in harness actually
  needs;
- ``GET /v1/models`` — the one served model
  (``root.common.api.model_id``);
- ``POST /v1/embeddings`` — batched pooled hidden states:
  :func:`embed_pool` runs the chain through its LAST HIDDEN layer
  (the logits head is skipped) in one jitted pass per
  (batch, width) bucket — the same one-shot prefill computation a
  decode admission pays, minus the cache insert — then mean-pools
  each row's real positions and L2-normalizes (the OpenAI unit-norm
  convention);
- ``POST /v1/classify`` — classifier scoring over the full chain:
  the last-position logits (exactly :func:`serving.prefill.prefill`'s
  TTFT edge) as per-class log-probabilities with top-k labels, which
  makes the Veles classifier surface servable rather than
  train-only.

The jax work here never runs on HTTP handler threads — the
scheduler's decode loop executes embed/score jobs between decode
boundaries (``InferenceScheduler.submit_embed`` /
``submit_score``), preserving the one-jax-thread invariant.
Parsing helpers raise ``ValueError`` with client-facing messages
(HTTP 400 material); the REST layer owns status codes and headers.
"""

import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.models.generate import (
    _StepClosure, _arch_sig, _check_positions, _device_params)
from veles_tpu.telemetry import track_jit


def _conf(name, default):
    from veles_tpu.config import root
    return root.common.api.get(name, default)


def model_id():
    """The model name this process serves under ``/v1/*``
    (``root.common.api.model_id``)."""
    return str(_conf("model_id", "veles-lm"))


def _bucket(n, floor=1):
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return b


# -- pooled embeddings (the serving.embed_pool jitted entry) ------------------

def embed_supported(forwards):
    """True when the chain can answer ``/v1/embeddings``: a prefill-
    capable chain with a distinct head unit to strip (the pooled
    states come from the layer UNDER the logits projection)."""
    from veles_tpu.serving.prefill import serving_supported
    return len(forwards) >= 2 and serving_supported(forwards)


def _make_embed_fn(forwards, window):
    cacheable = frozenset(i for i, u in enumerate(forwards)
                          if hasattr(u, "init_cache"))
    head = len(forwards) - 1   # the logits projection is skipped

    def run(params, prompt, lens):
        from veles_tpu import dtypes
        b, p = prompt.shape
        caches = {i: forwards[i].init_cache(b, window,
                                            dtypes.compute_dtype())
                  for i in cacheable}
        h = prompt
        for i, u in enumerate(forwards):
            if i == head:
                break
            if i in cacheable:
                h, caches[i] = u.apply_prefill(params[i], h,
                                               caches[i], lens=lens)
            else:
                h = u.apply(params[i], h)
        # h: [b, P, d] hidden states; mean-pool each row's REAL
        # positions (padding rows must not dilute the vector), then
        # L2-normalize — cosine similarity becomes a dot product
        mask = (jnp.arange(h.shape[1])[None, :]
                < lens[:, None]).astype(jnp.float32)
        pooled = (h.astype(jnp.float32) * mask[:, :, None]).sum(1) \
            / jnp.maximum(lens, 1).astype(jnp.float32)[:, None]
        norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
        return pooled / jnp.maximum(norm, 1e-12)
    return run


@functools.lru_cache(maxsize=32)
def _embed_cached(cache_key, closure):
    return track_jit("serving.embed_pool", jax.jit(closure.fn))


def clear_embed_cache():
    """Drop the compiled embed-pool cache (entries pin the chain's
    units — same lifetime note as ``generate.clear_decode_caches``)."""
    _embed_cached.cache_clear()


def embed_pool(forwards, prompt, prompt_lens):
    """Pooled embeddings for ``prompt`` [b, P] int32 (front-aligned
    rows, ``prompt_lens`` [b] real lengths): ONE jitted pass through
    the chain's hidden layers (head skipped), masked mean-pool,
    L2-normalized [b, d] f32.  Callers bucket b and P — each (b, P)
    pair is one compiled executable."""
    if not embed_supported(forwards):
        raise ValueError("chain cannot serve embeddings (needs a "
                         "prefill-capable chain with a head unit)")
    params = _device_params(forwards)
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    _check_positions(forwards, p)
    lens_np = numpy.asarray(prompt_lens, numpy.int32)
    if lens_np.shape != (b,) or lens_np.min() < 1 or lens_np.max() > p:
        raise ValueError("prompt_lens must be [batch] ints in "
                         "[1, %d]" % p)
    from veles_tpu import dtypes
    cache_key = (_arch_sig(forwards), b, p,
                 str(dtypes.compute_dtype()),
                 str(dtypes.matmul_precision()))
    fn = _embed_cached(cache_key,
                       _StepClosure(_make_embed_fn(forwards, p)))
    return fn(params, prompt, jnp.asarray(lens_np))


def _pad_rows(rows, width_cap):
    """Front-aligned [b_bucket, p_bucket] padding of ragged token
    rows: both axes power-of-two bucketed (compiled-executable
    economy), width capped at the serving window."""
    lens = [len(r) for r in rows]
    width = min(_bucket(max(lens), 8), int(width_cap))
    b = _bucket(len(rows), 1)
    padded = numpy.zeros((b, width), numpy.int32)
    for i, r in enumerate(rows):
        padded[i, :len(r)] = r
    lens_arr = numpy.ones((b,), numpy.int32)
    lens_arr[:len(rows)] = lens
    return padded, lens_arr


def pooled_embeddings(forwards, rows, window):
    """Batched ``/v1/embeddings`` execution: bucket + pad the rows,
    one :func:`embed_pool` pass, unpadded [n, d] float lists back."""
    padded, lens = _pad_rows(rows, window)
    out = numpy.asarray(embed_pool(forwards, padded, lens))
    return [out[i].tolist() for i in range(len(rows))]


def score_rows(forwards, rows, window):
    """Batched ``/v1/classify`` execution: the last-position logits
    of each row through the FULL chain (the prefill TTFT edge),
    log-softmaxed to per-class log-probabilities [n, classes]."""
    from veles_tpu.serving.prefill import prefill
    padded, lens = _pad_rows(rows, window)
    _, last = prefill(forwards, padded,
                      prompt_lens=lens, window=padded.shape[1])
    logits = numpy.asarray(last, numpy.float64)[:len(rows)]
    z = logits - logits.max(axis=-1, keepdims=True)
    logp = z - numpy.log(numpy.exp(z).sum(axis=-1, keepdims=True))
    return logp


# -- request parsing ----------------------------------------------------------

def parse_token_rows(raw, what="prompt"):
    """An OpenAI prompt/input: one token row or a batch of rows →
    list of non-empty int lists.  Raises ``ValueError`` (400
    material) on anything else — silently coercing junk would decode
    a phantom prompt."""
    if not isinstance(raw, list) or not raw:
        raise ValueError(
            "%s must be a non-empty token list or a batch of token "
            "lists (this engine is tokenizer-free: send token ids)"
            % what)
    rows = list(raw) if isinstance(raw[0], list) else [raw]
    out = []
    for r in rows:
        if not isinstance(r, list) or not r:
            raise ValueError("%s rows must be non-empty flat token "
                             "lists" % what)
        try:
            out.append([int(t) for t in r])
        except (TypeError, ValueError):
            raise ValueError("%s rows must be flat lists of int "
                             "token ids" % what)
    return out, not isinstance(raw[0], list)


def parse_completions(body):
    """``/v1/completions`` body → submit kwargs dict.  Client errors
    raise ``ValueError``; unsupported OpenAI parameters are REJECTED
    (a silently ignored ``n=4`` bills the client for answers it never
    gets)."""
    def _neutral_only(name, neutral):
        # SDKs send these at their neutral defaults — accept that,
        # reject anything that would change the output
        v = body.get(name)
        if v is not None and float(v) != float(neutral):
            raise ValueError("unsupported parameter %r (only the "
                             "neutral value %r)" % (name, neutral))
    _neutral_only("n", 1)
    _neutral_only("best_of", 1)
    _neutral_only("top_p", 1)
    _neutral_only("presence_penalty", 0)
    _neutral_only("frequency_penalty", 0)
    for unsupported in ("logprobs", "logit_bias", "suffix"):
        if body.get(unsupported):
            raise ValueError("unsupported parameter %r"
                             % unsupported)
    rows, squeeze = parse_token_rows(body.get("prompt"))
    try:
        steps = int(body.get("max_tokens", 16))
    except (TypeError, ValueError):
        raise ValueError("max_tokens must be an int")
    if steps < 1:
        raise ValueError("max_tokens must be >= 1")
    try:
        temperature = float(body.get("temperature") or 0.0)
        top_k = int(body.get("top_k") or 0)
    except (TypeError, ValueError):
        raise ValueError("temperature must be a number and top_k an "
                         "int")
    stop = body.get("stop")
    if stop is not None:
        try:
            stop = int(stop)
        except (TypeError, ValueError):
            raise ValueError("stop must be an int token id (this "
                             "engine is tokenizer-free)")
    seed = body.get("seed")
    if seed is not None:
        try:
            seed = int(seed)
        except (TypeError, ValueError):
            raise ValueError("seed must be an int")
    return {
        "rows": rows, "squeeze": squeeze, "steps": steps,
        "temperature": temperature, "top_k": top_k, "stop": stop,
        "seed": seed, "stream": bool(body.get("stream")),
        "echo": bool(body.get("echo")),
        "priority": body.get("priority"),
        "model": str(body.get("model") or model_id()),
    }


# -- response shaping ---------------------------------------------------------

def completion_id():
    return "cmpl-%s" % os.urandom(12).hex()


def text_of(tokens):
    """The ``text`` rendering of a token list: space-separated
    decimal ids (tokenizer-free engine — see module docstring)."""
    return " ".join(str(int(t)) for t in tokens)


def finish_reason(generated, steps, stop):
    return "stop" if (stop is not None and generated
                      and generated[-1] == stop) else "length"


def completion_choice(index, prompt, generated, params):
    toks = (list(prompt) + list(generated)) if params["echo"] \
        else list(generated)
    return {"index": index, "text": text_of(toks), "tokens": toks,
            "finish_reason": finish_reason(generated,
                                           params["steps"],
                                           params["stop"]),
            "logprobs": None}


def usage_of(rows, generated_counts):
    p = sum(len(r) for r in rows)
    c = sum(generated_counts)
    return {"prompt_tokens": p, "completion_tokens": c,
            "total_tokens": p + c}


def completion_reply(cid, created, model, choices, usage):
    return {"id": cid, "object": "text_completion",
            "created": created, "model": model, "choices": choices,
            "usage": usage}


def completion_chunk(cid, created, model, index, tokens,
                     finish=None, usage=None, trace_id=None):
    """One SSE chunk of a streaming completion: the newly accepted
    tokens (spec bursts arrive together), finish_reason/usage — and
    the request ``trace_id`` for server-side correlation — only on
    the terminal chunk (the OpenAI shape, plus the non-standard
    trace field this tokenizer-free engine adds)."""
    out = {"id": cid, "object": "text_completion", "created": created,
           "model": model,
           "choices": [{"index": index, "text": text_of(tokens),
                        "tokens": [int(t) for t in tokens],
                        "finish_reason": finish, "logprobs": None}]}
    if usage is not None:
        out["usage"] = usage
    if trace_id is not None:
        out["trace_id"] = trace_id
    return out


def models_reply():
    return {"object": "list",
            "data": [{"id": model_id(), "object": "model",
                      "created": int(time.time()),
                      "owned_by": "veles_tpu"}]}


def embeddings_reply(model, vectors, rows):
    return {"object": "list", "model": model,
            "data": [{"object": "embedding", "index": i,
                      "embedding": v}
                     for i, v in enumerate(vectors)],
            "usage": {"prompt_tokens": sum(len(r) for r in rows),
                      "total_tokens": sum(len(r) for r in rows)}}


def classify_reply(model, logp, rows, top):
    """Per-row class scores: full log-probability vector plus the
    top-k (label = class index — the Veles classifier heads are
    index-labeled)."""
    data = []
    for i in range(len(rows)):
        order = numpy.argsort(-logp[i])[:max(1, int(top))]
        data.append({
            "index": i,
            "label": int(order[0]),
            "top": [{"label": int(c),
                     "logprob": round(float(logp[i][c]), 6)}
                    for c in order],
            "logprobs": [round(float(x), 6) for x in logp[i]],
        })
    return {"object": "list", "model": model, "data": data,
            "usage": {"prompt_tokens": sum(len(r) for r in rows),
                      "total_tokens": sum(len(r) for r in rows)}}
