"""Disaggregated prefill/decode — the KV handoff wire format.

DistServe-style disaggregation (Zhong et al., 2024) splits a serving
fleet into PREFILL specialists (chunked prefill, no decode loop
tenancy) and DECODE specialists (token loop only), removing the
prefill/decode interference chunked prefill merely bounds.  The
handoff is the paged cache's own block transport: a prefill replica
finishes a prompt, gathers the slot's blocks RAW
(``PagedKVCache.export_blocks`` — int8 stays int8, scales ride
along), and parks the record under a handle; ``GET
/serving/kv_export/<handle>`` serves it in the JSON envelope below;
the decode replica scatters the blocks into its own table
(``import_blocks``) and samples the first token from the exported
last-position logits — the stream is then identical to the colocated
path (fp32 bit-exact; int8 blocks import unrequantized, so the
resident bytes match the exporter's exactly).

Wire format (JSON; arrays as base64 of C-order bytes)::

    {"handle": "...", "prompt": [ids...], "length": P,
     "kv_dtype": "fp32"|"int8", "block_size": 16,
     "logits": {"b64": ..., "dtype": "float32", "shape": [vocab]},
     "layers": {"<chain idx>": {"k": <arr>, "v": <arr>
                                [, "k_scale": <arr>, "v_scale": <arr>]}}}

K/V arrays are ``[ceil(P / block_size), block_size, d]`` in the
exporting pool's storage dtype; scale arrays are
``[blocks, block_size]`` f32.  Positions ≥ P in the last block hold
the staging zeros the colocated insert would have written — the
causal mask never reads them, and carrying them keeps the import a
plain block scatter.  Importer validation (dtype/block-size/shape
mismatches are client errors) lives in
``InferenceScheduler.submit_imported``.

Binary wire (``application/x-veles-kv``)::

    b"VKV1" | u32 header_len (LE) | header JSON (UTF-8) | raw bytes

The header carries every scalar field of the JSON envelope plus an
``arrays`` manifest — ``[{"key": ["logits"] | ["layers", "<i>",
"<name>"], "dtype": ..., "shape": [...]}, ...]`` — and the payload is
the C-order bytes of each manifest entry concatenated in order.  The
decoder slices ``numpy.frombuffer`` views straight out of the frame
(no base64, no per-element JSON), which is what makes this the fast
path: encode is one memcpy per array, decode is zero-copy.  Both
disagg handoffs and the router's peer prefix fetches speak it;
``logits`` is optional so prefix records (blocks only, no sampling
state) reuse the same frame.  An ``extra`` header field carries
side-channel parameters (e.g. the decode hop's sampler settings) so
binary POSTs need no JSON wrapper.
"""

import base64
import json
import struct
import uuid

import numpy

#: Content-Type / Accept token for the binary frame below.
WIRE_CONTENT_TYPE = "application/x-veles-kv"

_MAGIC = b"VKV1"


def mint_handle():
    """An unguessable export handle (the record may hold model
    activations — the handle is the only capability to fetch it)."""
    return uuid.uuid4().hex


def _np_dtype(name):
    """``numpy.dtype`` by name, including the ml_dtypes extension
    types numpy cannot look up itself (a bfloat16-pool export names
    its storage dtype "bfloat16")."""
    try:
        return numpy.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return numpy.dtype(getattr(ml_dtypes, name))
        except (AttributeError, TypeError):
            raise ValueError("unknown kv wire dtype %r" % (name,))


def _raw(a):
    """A C-order bytes-like of ``a`` — the zero-copy memoryview when
    the dtype speaks the buffer protocol, one memcpy (``tobytes``)
    for the extension dtypes that refuse it (bfloat16's 'E')."""
    try:
        return a.data
    except (TypeError, ValueError, BufferError):
        return a.tobytes()


def _encode_array(a):
    a = numpy.ascontiguousarray(a)
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _decode_array(obj):
    raw = base64.b64decode(obj["b64"])
    return numpy.frombuffer(raw, dtype=_np_dtype(obj["dtype"])) \
        .reshape([int(s) for s in obj["shape"]]).copy()


def encode_export(record):
    """Serialize a scheduler export record (numpy arrays) into the
    JSON-safe envelope above."""
    out = {
        "handle": record["handle"],
        "prompt": [int(t) for t in record["prompt"]],
        "length": int(record["length"]),
        "kv_dtype": record["kv_dtype"],
        "block_size": int(record["block_size"]),
        "layers": {str(i): {n: _encode_array(a)
                            for n, a in layer.items()}
                   for i, layer in record["layers"].items()},
    }
    if "logits" in record:
        out["logits"] = _encode_array(record["logits"])
    return out


def decode_export(obj):
    """Parse the JSON envelope back into the numpy record
    ``submit_imported`` consumes.  Raises ``ValueError`` on a
    malformed payload (client error, not a replica fault)."""
    try:
        rec = {
            "handle": str(obj["handle"]),
            "prompt": [int(t) for t in obj["prompt"]],
            "length": int(obj["length"]),
            "kv_dtype": str(obj["kv_dtype"]),
            "block_size": int(obj["block_size"]),
            "layers": {int(i): {n: _decode_array(a)
                                for n, a in layer.items()}
                       for i, layer in obj["layers"].items()},
        }
        if obj.get("logits") is not None:
            rec["logits"] = _decode_array(obj["logits"])
        return rec
    except (KeyError, TypeError, AttributeError) as e:
        raise ValueError("malformed kv export payload: %r" % (e,))


def record_nbytes(record):
    """Payload size of a record's arrays in bytes — the budgeting
    unit for the export table's byte cap and the host tier."""
    n = record["logits"].nbytes if "logits" in record else 0
    for layer in record["layers"].values():
        for a in layer.values():
            n += a.nbytes
    return n


def _manifest(record):
    """Deterministic array order for the binary frame: logits first
    (when present), then layers by chain index, names sorted."""
    entries = []
    if "logits" in record:
        entries.append((("logits",), record["logits"]))
    for i in sorted(record["layers"]):
        layer = record["layers"][i]
        for n in sorted(layer):
            entries.append((("layers", str(i), n), layer[n]))
    return entries


def encode_export_binary(record, extra=None):
    """Frame a record as ``application/x-veles-kv`` bytes (see module
    docstring).  ``extra`` (JSON-safe dict) rides in the header —
    binary POST bodies carry their side parameters there instead of a
    JSON wrapper."""
    entries = [(key, numpy.ascontiguousarray(a))
               for key, a in _manifest(record)]
    header = {
        "handle": record["handle"],
        "prompt": [int(t) for t in record["prompt"]],
        "length": int(record["length"]),
        "kv_dtype": record["kv_dtype"],
        "block_size": int(record["block_size"]),
        "arrays": [{"key": list(key), "dtype": str(a.dtype),
                    "shape": list(a.shape)} for key, a in entries],
    }
    if extra:
        header["extra"] = extra
    hjson = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return b"".join([_MAGIC, struct.pack("<I", len(hjson)), hjson]
                    + [_raw(a) for _, a in entries])


def decode_export_binary(blob):
    """Parse an ``application/x-veles-kv`` frame back into ``(record,
    extra)``.  Array contents are zero-copy ``frombuffer`` views into
    ``blob`` (read-only — importers scatter them, never mutate).
    Raises ``ValueError`` on a malformed frame."""
    try:
        view = memoryview(blob)
        if bytes(view[:4]) != _MAGIC:
            raise ValueError("bad kv wire magic")
        (hlen,) = struct.unpack("<I", view[4:8])
        header = json.loads(bytes(view[8:8 + hlen]).decode("utf-8"))
        record = {
            "handle": str(header["handle"]),
            "prompt": [int(t) for t in header["prompt"]],
            "length": int(header["length"]),
            "kv_dtype": str(header["kv_dtype"]),
            "block_size": int(header["block_size"]),
            "layers": {},
        }
        off = 8 + hlen
        for ent in header["arrays"]:
            dtype = _np_dtype(str(ent["dtype"]))
            shape = [int(s) for s in ent["shape"]]
            nbytes = dtype.itemsize * int(numpy.prod(shape, dtype=numpy.int64))
            a = numpy.frombuffer(view[off:off + nbytes],
                                 dtype=dtype).reshape(shape)
            off += nbytes
            key = ent["key"]
            if key == ["logits"]:
                record["logits"] = a
            elif len(key) == 3 and key[0] == "layers":
                record["layers"].setdefault(int(key[1]), {})[
                    str(key[2])] = a
            else:
                raise ValueError("bad array key %r" % (key,))
        if off != len(view):
            raise ValueError("kv wire length mismatch")
        return record, header.get("extra") or {}
    except (KeyError, TypeError, AttributeError, struct.error,
            json.JSONDecodeError) as e:
        raise ValueError("malformed kv wire frame: %r" % (e,))


def quantize_record(record):
    """int8-quantize a fp32 record's K/V blocks in flight (PR 12's
    per-row absmax machinery), shrinking the wire ~4x.  Lossy — never
    used on parity-critical paths (disagg keeps the pool dtype); the
    importer sees a regular int8 record with inline scales.  int8
    records pass through untouched."""
    if record["kv_dtype"] != "fp32":
        return record
    from ..ops import paged_attention as pa
    layers = {}
    for i, layer in record["layers"].items():
        k_q, k_s = pa.quantize_kv_rows(layer["k"])
        v_q, v_s = pa.quantize_kv_rows(layer["v"])
        layers[i] = {"k": numpy.asarray(k_q), "v": numpy.asarray(v_q),
                     "k_scale": numpy.asarray(k_s, dtype=numpy.float32),
                     "v_scale": numpy.asarray(v_s, dtype=numpy.float32)}
    out = dict(record)
    out["kv_dtype"] = "int8"
    out["layers"] = layers
    return out
