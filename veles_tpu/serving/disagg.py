"""Disaggregated prefill/decode — the KV handoff wire format.

DistServe-style disaggregation (Zhong et al., 2024) splits a serving
fleet into PREFILL specialists (chunked prefill, no decode loop
tenancy) and DECODE specialists (token loop only), removing the
prefill/decode interference chunked prefill merely bounds.  The
handoff is the paged cache's own block transport: a prefill replica
finishes a prompt, gathers the slot's blocks RAW
(``PagedKVCache.export_blocks`` — int8 stays int8, scales ride
along), and parks the record under a handle; ``GET
/serving/kv_export/<handle>`` serves it in the JSON envelope below;
the decode replica scatters the blocks into its own table
(``import_blocks``) and samples the first token from the exported
last-position logits — the stream is then identical to the colocated
path (fp32 bit-exact; int8 blocks import unrequantized, so the
resident bytes match the exporter's exactly).

Wire format (JSON; arrays as base64 of C-order bytes)::

    {"handle": "...", "prompt": [ids...], "length": P,
     "kv_dtype": "fp32"|"int8", "block_size": 16,
     "logits": {"b64": ..., "dtype": "float32", "shape": [vocab]},
     "layers": {"<chain idx>": {"k": <arr>, "v": <arr>
                                [, "k_scale": <arr>, "v_scale": <arr>]}}}

K/V arrays are ``[ceil(P / block_size), block_size, d]`` in the
exporting pool's storage dtype; scale arrays are
``[blocks, block_size]`` f32.  Positions ≥ P in the last block hold
the staging zeros the colocated insert would have written — the
causal mask never reads them, and carrying them keeps the import a
plain block scatter.  Importer validation (dtype/block-size/shape
mismatches are client errors) lives in
``InferenceScheduler.submit_imported``.
"""

import base64
import uuid

import numpy


def mint_handle():
    """An unguessable export handle (the record may hold model
    activations — the handle is the only capability to fetch it)."""
    return uuid.uuid4().hex


def _encode_array(a):
    a = numpy.ascontiguousarray(a)
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _decode_array(obj):
    raw = base64.b64decode(obj["b64"])
    return numpy.frombuffer(raw, dtype=numpy.dtype(obj["dtype"])) \
        .reshape([int(s) for s in obj["shape"]]).copy()


def encode_export(record):
    """Serialize a scheduler export record (numpy arrays) into the
    JSON-safe envelope above."""
    return {
        "handle": record["handle"],
        "prompt": [int(t) for t in record["prompt"]],
        "length": int(record["length"]),
        "kv_dtype": record["kv_dtype"],
        "block_size": int(record["block_size"]),
        "logits": _encode_array(record["logits"]),
        "layers": {str(i): {n: _encode_array(a)
                            for n, a in layer.items()}
                   for i, layer in record["layers"].items()},
    }


def decode_export(obj):
    """Parse the JSON envelope back into the numpy record
    ``submit_imported`` consumes.  Raises ``ValueError`` on a
    malformed payload (client error, not a replica fault)."""
    try:
        return {
            "handle": str(obj["handle"]),
            "prompt": [int(t) for t in obj["prompt"]],
            "length": int(obj["length"]),
            "kv_dtype": str(obj["kv_dtype"]),
            "block_size": int(obj["block_size"]),
            "logits": _decode_array(obj["logits"]),
            "layers": {int(i): {n: _decode_array(a)
                                for n, a in layer.items()}
                       for i, layer in obj["layers"].items()},
        }
    except (KeyError, TypeError, AttributeError) as e:
        raise ValueError("malformed kv export payload: %r" % (e,))
