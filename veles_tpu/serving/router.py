"""Health-aware HTTP router over N serving replicas — the tier that
makes the fleet fail like a fleet instead of like its weakest process.

One engine process (``restful_api.py`` + ``serving/scheduler.py``) is
both the availability and the throughput ceiling: a crash takes the
service down and there is no way to restart under live traffic.  The
:class:`Router` fronts N replicas and composes the primitives PRs 3–7
shipped per process (``GET /healthz``, ``POST /drain``, structured
JSON errors with ``Retry-After``, the :mod:`veles_tpu.faults`
registry) into fleet behavior:

- **health-aware routing** — a poll task GETs every replica's
  ``/healthz`` (and piggybacks ``/serving/metrics``) each
  ``health_interval``; replicas reporting ``"draining"`` or
  ``"halted"``, or unreachable twice in a row, leave the rotation
  without tripping a breaker.  Among eligible replicas the router
  picks **least-outstanding-requests**, with optional prompt-prefix /
  session **affinity** (rendezvous hash over the first
  ``affinity_tokens`` prompt tokens, or the ``X-Veles-Session``
  header) so repeated prompts land on the replica already holding
  their KV blocks;
- **circuit breakers** — per replica: ``closed`` → ``open`` after
  ``breaker_failures`` consecutive transport failures/timeouts/5xx;
  after ``breaker_cooldown`` the breaker goes ``half_open`` and
  admits a SINGLE probe request — success (any HTTP reply, 503
  included: backpressure proves liveness) closes it, failure
  re-opens.  State rides ``veles_router_breaker_state{replica}``;
- **retries** — a failed attempt (connection error, timeout, 5xx)
  retries on another replica under a per-request budget
  (``retries`` total attempts) with capped exponential backoff plus
  jitter (the coordinator ``_backoff`` shape), never past the
  request deadline; when every attempt fails, the reply propagates
  ``tokens_generated`` from the best attempt so the client knows
  what its budget bought;
- **hedging** — for idempotent requests only (greedy, or seeded
  sampling: the reply is the same whichever replica answers), a
  straggling primary attempt is hedged once against a second replica
  after ``hedge_delay`` seconds; the first deliverable reply wins
  and the loser is cancelled (0 disables);
- **load shedding** — once no replica is eligible (all open,
  draining, unhealthy or saturated) the router answers a structured
  503 with ``Retry-After`` instead of queueing unbounded;
- **rolling restarts** — :meth:`drain_replica` marks the replica
  draining router-side FIRST (no new traffic — explicitly NOT a
  breaker trip), then POSTs ``/drain`` (with the
  ``root.common.api.admin_token`` bearer when configured, so remote
  replicas accept it); :class:`veles_tpu.serving.fleet.Fleet`
  orchestrates drain → wait drained → restart → re-admit over the
  whole fleet with zero failed client requests.

- **streaming + the OpenAI facade** — ``POST /generate`` /
  ``/v1/completions`` bodies with ``"stream": true`` proxy as SSE
  **frame by frame**.  Replayable ``/generate`` streams (single
  row, greedy or seed-pinned) get **transparent mid-stream
  failover**: the router records the body and every token frame it
  forwarded, and when the pinned replica dies or errors mid-stream
  it resubmits through the replica ``resume_tokens`` lane — the
  continuation re-prefills prompt + prefix, samples at draw counter
  ``len(forwarded)`` and splices into the open connection
  bit-identical to an uninterrupted run, with zero client-visible
  error frames (``veles_router_stream_failovers_total{outcome}``).
  Non-replayable streams (multi-row, unseeded sampling, the /v1
  facade) keep the pin-and-truncate contract; hedging never arms
  for streams.  A client that disconnects mid-stream tears down the
  upstream connection — the active leg AND any resume in flight —
  which cancels the request on the replica and frees its KV blocks.
  ``/v1/completions``, ``/v1/embeddings``, ``/v1/classify`` and
  ``GET /v1/models`` forward with the same affinity/retry/breaker
  machinery as ``/generate``.

- **cache-topology routing + prefix shipping (PR 19)** — each
  metrics poll carries the replica's ``prefix_digests``
  advertisement (rolling crc32 path digests of every resident
  prefix, device trie + host tier).  For single-row ``/generate``
  bodies the router computes the prompt's own digests and routes to
  the replica holding the LONGEST resident prefix — an upgrade over
  blind crc32 affinity, which spreads identical prompts by hash
  regardless of who is actually warm.  When a PEER holds a prefix
  ``prefix_fetch_min`` blocks longer than the chosen target's, the
  router first SHIPS it: ``POST /serving/prefix_export`` on the
  peer (binary KV wire, ``application/x-veles-kv``) → ``POST
  /serving/prefix_import`` on the target — so one replica's warm
  cache seeds another's and a drained replica's warmth is rescued
  before it dies.  Both steps are best-effort: any failure counts
  ``veles_router_prefix_peer_fetch_fails_total`` and the request
  proceeds cold.  Fault point ``router.prefix.fetch`` (keyed by the
  holder id) injects exactly the peer-death window.

- **request tracing + SLOs** — every request gets a trace id at the
  edge (``X-Veles-Trace``, accepted-or-minted, echoed on EVERY reply
  including structured errors) that is propagated to the replica; the
  routed request is a ``router.request`` span and each retry/hedge
  attempt a ``router.attempt`` child span in the JSONL event sink
  (merge with the replica logs via ``telemetry.trace_export
  --request <id>``).  ``GET /debug/requests`` lists the live
  in-flight proxy table, and ``/router/state`` carries the fleet-tail
  SLO block (per-class e2e good/bad + multi-window burn rates,
  ``root.common.slo.*``).

Fault points ``router.forward`` and ``router.replica.health`` (keyed
by replica id) wire the router into the injection registry; they run
in the executor so a ``hang``/``delay`` stalls one attempt, not the
event loop.  Everything is asyncio on ONE background loop thread —
replica state is only ever mutated there, so routing decisions need
no locks; public entry points marshal through the loop.

Config: ``root.common.router.*`` (every knob also a constructor
kwarg); see ``config.py`` for the full table.
"""

import asyncio
import itertools
import json
import random
import threading
import time
import zlib

from veles_tpu import faults
from veles_tpu.logger import Logger, events
from veles_tpu.serving.disagg import WIRE_CONTENT_TYPE
from veles_tpu.serving.metrics import RouterMetrics
from veles_tpu.serving.prefix_cache import chunk_digests
from veles_tpu.telemetry import reqtrace
from veles_tpu.telemetry.spans import next_span_id
from veles_tpu.tenant import TenantAdmission

#: outcomes the router hands to the client as-is (2xx/3xx/4xx — the
#: replica spoke; 5xx and transport errors are the router's to mask)
_DELIVERABLE_BELOW = 500


def _router_conf(name, default):
    from veles_tpu.config import root
    return root.common.router.get(name, default)


class _Replica(object):
    """Router-side view of one replica.  Mutated ONLY on the router's
    event-loop thread (the no-locks invariant of this module)."""

    __slots__ = ("id", "host", "port", "outstanding", "healthy",
                 "status", "draining", "marked_draining",
                 "health_failures", "breaker", "failures",
                 "opened_at", "probing", "saturated_until",
                 "last_health", "last_metrics", "requests", "role",
                 "last_scrape", "scrape_failed", "prefix_digests")

    def __init__(self, replica_id, host, port):
        self.id = str(replica_id)
        self.host = host
        self.port = int(port)
        self.role = "both"        # /healthz advertises the real one
        self.outstanding = 0      # in-flight forwards (routing load)
        self.healthy = False      # until the first probe passes
        self.status = "unknown"
        self.draining = False     # healthz said so (or marked below)
        self.marked_draining = False  # router-initiated drain latch
        self.health_failures = 0  # consecutive failed probes
        self.breaker = "closed"   # closed | open | half_open
        self.failures = 0         # consecutive forward failures
        self.opened_at = 0.0
        self.probing = False      # the half-open single probe is out
        self.saturated_until = 0.0  # 503 Retry-After backoff window
        self.last_health = None
        self.last_metrics = None
        self.last_scrape = None   # latest /metrics exposition text
        self.scrape_failed = False
        #: cache-topology advertisement off the last metrics poll:
        #: rolling digests of every prefix resident on the replica
        #: (device trie + host tier) — the routing warmth signal
        self.prefix_digests = frozenset()
        self.requests = 0

    def view(self):
        return {
            "id": self.id, "host": self.host, "port": self.port,
            "healthy": self.healthy, "status": self.status,
            "role": self.role,
            "tp": (self.last_health or {}).get("tp"),
            "draining": self.draining, "breaker": self.breaker,
            "outstanding": self.outstanding,
            "requests": self.requests,
            "consecutive_failures": self.failures,
            "queue_depth": (self.last_metrics or {}).get(
                "queue_depth"),
            # slot occupancy (the controller's scale-down and
            # role-ratio signals read these off replica_state())
            "active_slots": (self.last_metrics or {}).get(
                "active_slots"),
            "max_slots": (self.last_metrics or {}).get(
                "max_slots"),
            "kv_blocks_used": (self.last_metrics or {}).get(
                "kv_blocks_used"),
            "kv_blocks_free": (self.last_metrics or {}).get(
                "kv_blocks_free"),
            # goodput accounting: real throughput + how much of each
            # padded batch carried requests (PR 14 dashboard columns)
            "goodput_tokens_per_sec": (self.last_metrics or {}).get(
                "goodput_tokens_per_sec"),
            "bucket_padding_efficiency": (
                self.last_metrics or {}).get(
                "bucket_padding_efficiency"),
            # the observable payoff of prefix/session affinity: a
            # well-aimed router keeps this high on repeat traffic
            "prefix_hit_rate": (self.last_metrics or {}).get(
                "prefix_cache_hit_rate"),
            # tiered-KV topology: how much warmth the replica
            # advertises, and how much of it lives in host RAM
            "prefix_digests": len(self.prefix_digests),
            "kv_host_blocks": (self.last_metrics or {}).get(
                "kv_host_blocks"),
            "spec_accept_rate": (self.last_metrics or {}).get(
                "spec_accept_rate"),
            # per-priority-class QoS counters (TTFT p95, preempts,
            # sheds by class) straight off /serving/metrics — the
            # observable half of preemptive scheduling
            "classes": (self.last_metrics or {}).get("classes"),
        }


class _Outcome(object):
    """One normalized forward attempt: either a replica reply
    (``status``/``headers``/``body``) or a transport ``error``."""

    __slots__ = ("rep", "status", "headers", "body", "error")

    def __init__(self, rep, status=None, headers=None, body=b"",
                 error=None):
        self.rep = rep
        self.status = status
        self.headers = headers or {}
        self.body = body
        self.error = error

    @property
    def deliverable(self):
        return self.error is None and self.status < _DELIVERABLE_BELOW

    def tokens_generated(self):
        """The partial-decode count a failed attempt's structured
        error body carried (408/5xx material), else None."""
        try:
            err = json.loads(self.body.decode()).get("error", {})
            return int(err["tokens_generated"])
        except Exception:
            return None


class Router(Logger):
    """Asyncio HTTP router over N serving replicas (module docstring
    has the behavior contract).  ``start()`` binds and returns self;
    ``add_replica``/``remove_replica``/``drain_replica`` are
    thread-safe; ``stop()`` tears the loop down."""

    def __init__(self, host="127.0.0.1", port=0, replicas=(),
                 health_interval=None, health_timeout=None,
                 breaker_failures=None, breaker_cooldown=None,
                 retries=None, retry_delay=None, retry_cap=None,
                 hedge_delay=None, affinity_tokens=None,
                 request_timeout=None, shed_retry_after=None,
                 prefix_routing=None, prefix_fetch=None,
                 prefix_fetch_min=None):
        super(Router, self).__init__()
        self.host = host
        self.port = int(port)
        self.health_interval = float(
            _router_conf("health_interval", 0.5)
            if health_interval is None else health_interval)
        self.health_timeout = float(
            _router_conf("health_timeout", 1.0)
            if health_timeout is None else health_timeout)
        self.breaker_failures = int(
            _router_conf("breaker_failures", 3)
            if breaker_failures is None else breaker_failures)
        self.breaker_cooldown = float(
            _router_conf("breaker_cooldown", 2.0)
            if breaker_cooldown is None else breaker_cooldown)
        self.retries = int(_router_conf("retries", 3)
                           if retries is None else retries)
        self.retry_delay = float(_router_conf("retry_delay", 0.05)
                                 if retry_delay is None
                                 else retry_delay)
        self.retry_cap = float(_router_conf("retry_cap", 2.0)
                               if retry_cap is None else retry_cap)
        self.hedge_delay = float(_router_conf("hedge_delay", 0.0)
                                 if hedge_delay is None
                                 else hedge_delay)
        self.affinity_tokens = int(
            _router_conf("affinity_tokens", 16)
            if affinity_tokens is None else affinity_tokens)
        if request_timeout is None:
            request_timeout = _router_conf("request_timeout", None)
        if request_timeout is None:
            from veles_tpu.config import root
            request_timeout = root.common.serving.get(
                "request_timeout", 120.0)
        self.request_timeout = float(request_timeout or 120.0)
        self.shed_retry_after = int(
            _router_conf("shed_retry_after", 2)
            if shed_retry_after is None else shed_retry_after)
        #: tiered-KV topology (PR 19): route /generate on the
        #: longest advertised resident prefix instead of blind crc32
        #: affinity, and ship a peer's longer prefix onto the target
        #: when it leads by >= prefix_fetch_min blocks
        self.prefix_routing = bool(
            _router_conf("prefix_routing", True)
            if prefix_routing is None else prefix_routing)
        self.prefix_fetch = bool(
            _router_conf("prefix_fetch", True)
            if prefix_fetch is None else prefix_fetch)
        self.prefix_fetch_min = int(
            _router_conf("prefix_fetch_min", 2)
            if prefix_fetch_min is None else prefix_fetch_min)
        self.stats = RouterMetrics()
        #: per-tenant identity + admission (tenant/admission.py):
        #: tagging is always on, the bucket/lane enforce only when
        #: root.common.tenant.enabled
        self.tenants = TenantAdmission()
        #: the router-tier alert engine (telemetry/alerts.py),
        #: created at start() when root.common.alerts.enabled
        self.alerts = None
        #: the router-tier history store (telemetry/tsdb.py),
        #: created at start() when root.common.tsdb.enabled — its
        #: ticker samples the FEDERATED merge, so fleet-wide history
        #: survives replica churn (a dead replica's counted work
        #: stays in the buckets it landed in)
        self.tsdb = None
        #: request tracing (telemetry/reqtrace.py), read once — the
        #: per-attempt gate is an attribute test
        self._tron = reqtrace.enabled()
        self._seed_replicas = [tuple(r) for r in replicas]
        self._replicas = {}        # id -> _Replica (loop thread only)
        self._inflight = {}        # seq -> live request info (loop
        #                            thread only, like _replicas)
        self._req_seq = itertools.count(1)
        self._lock = threading.Lock()
        self._loop = None
        self._thread = None
        self._server = None
        self._health_task = None
        self._ready = threading.Event()

    # -- lifecycle -------------------------------------------------------

    def start(self):
        with self._lock:  # two racing start()s must not spawn 2 loops
            if self._thread is not None:
                self._ready.wait(60)
                return self
            self._loop = asyncio.new_event_loop()
            self._thread = threading.Thread(
                target=self._loop.run_forever, daemon=True,
                name="serving-router")
        self._thread.start()
        asyncio.run_coroutine_threadsafe(
            self._bind(), self._loop).result(60)
        for spec in self._seed_replicas:
            self.add_replica(*spec)
        self._ready.set()
        # flight-recorder / debug surface (weakly held)
        reqtrace.register("router", self)
        from veles_tpu.config import root
        if root.common.tsdb.get("enabled", True):
            from veles_tpu.telemetry.tsdb import TimeSeriesStore

            def _fleet_collect():
                # the store's ticker thread marshals onto the router
                # loop for the merge; a stopped/stopping router just
                # yields an empty sample instead of raising forever
                try:
                    return self._call(self._fleet_async())
                except Exception:
                    return []
            self.tsdb = TimeSeriesStore(
                name="router", collect=_fleet_collect).start()
        if root.common.alerts.get("enabled", True):
            from veles_tpu.telemetry.alerts import AlertEngine
            # no providers: GET /alerts is answered ON the router
            # loop, and a provider marshalling back into that loop
            # (replica_state) would deadlock the handler.  The trend
            # rules read the router's own store — fleet-merged
            # history, not any single replica's
            self.alerts = AlertEngine(name="router",
                                      tsdb=self.tsdb).start()
        self.info("router on http://%s:%d -> %d replica(s)",
                  self.host, self.port, len(self._seed_replicas))
        return self

    async def _bind(self):
        self._server = await asyncio.start_server(
            self._serve_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._health_task = asyncio.ensure_future(self._health_loop())

    def stop(self):
        if self.tsdb is not None:
            self.tsdb.stop()
        if self.alerts is not None:
            self.alerts.stop()
        with self._lock:
            loop, self._loop = self._loop, None
            thread, self._thread = self._thread, None
        if loop is None:
            return
        asyncio.run_coroutine_threadsafe(
            self._shutdown(), loop).result(30)
        loop.call_soon_threadsafe(loop.stop)
        if thread is not None:
            thread.join(30)
        loop.close()

    async def _shutdown(self):
        if self._health_task is not None:
            self._health_task.cancel()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    def _call(self, coro):
        """Run a coroutine on the router loop from any thread."""
        with self._lock:
            loop = self._loop
        if loop is None:
            raise RuntimeError("router is not running")
        return asyncio.run_coroutine_threadsafe(coro, loop).result(60)

    # -- replica registry ------------------------------------------------

    def add_replica(self, host, port, replica_id=None):
        """Register a replica and probe it once (so a freshly started
        healthy replica is routable without waiting out a poll
        period).  Returns the replica id."""
        rid = str(replica_id or "%s:%d" % (host, int(port)))
        return self._call(self._add(rid, host, int(port)))

    async def _add(self, rid, host, port):
        rep = _Replica(rid, host, port)
        self._replicas[rid] = rep
        self.stats.record_breaker(rid, "closed")
        await self._probe(rep)
        return rid

    def remove_replica(self, replica_id):
        """Deregister (a stopped/dead replica); in-flight forwards to
        it finish or fail on their own."""
        return self._call(self._remove(str(replica_id)))

    async def _remove(self, rid):
        gone = self._replicas.pop(rid, None) is not None
        if gone:
            # drop the labeled series so a deregistered replica's
            # replica_up=0 cannot keep an unreachable alert firing
            self.stats.forget_replica(rid)
        return gone

    def replica_state(self):
        """Monitoring snapshot: per-replica view + router counters."""
        return self._call(self._state())

    async def _state(self):
        return {
            "replicas": [r.view() for r in self._replicas.values()],
            "eligible": len(self._pickable(time.monotonic())),
            "router": self.stats.snapshot(),
        }

    def drain_replica(self, replica_id, timeout=30.0):
        """Begin draining one replica for a rolling restart: the
        router stops routing to it IMMEDIATELY (a drain is not a
        breaker trip), then POSTs ``/drain`` (bearer admin token when
        configured).  Returns the replica's drain reply dict."""
        return self._call(self._drain(str(replica_id), timeout))

    async def _drain(self, rid, timeout):
        rep = self._replicas.get(rid)
        if rep is None:
            raise KeyError("unknown replica %r" % rid)
        rep.marked_draining = rep.draining = True
        self.stats.record_drain(rid)
        headers = {}
        from veles_tpu.config import root
        token = root.common.api.get("admin_token", None)
        if token:
            headers["Authorization"] = "Bearer %s" % token
        status, _, body = await asyncio.wait_for(
            self._http(rep, "POST", "/drain", b"{}", headers),
            timeout)
        if status >= 400:
            raise RuntimeError("drain of %s failed: HTTP %d" %
                               (rid, status))
        return json.loads(body.decode() or "{}")

    # -- routing ---------------------------------------------------------

    def _eligible(self, rep, now):
        if rep.draining or not rep.healthy:
            return False
        if now < rep.saturated_until:
            return False
        if rep.breaker == "open":
            if now - rep.opened_at < self.breaker_cooldown:
                return False
            self._breaker_to(rep, "half_open")
        if rep.breaker == "half_open" and rep.probing:
            return False  # single probe at a time
        return True

    @staticmethod
    def _serves(rep, phase):
        """Role gate for one dispatch phase: DECODE-phase traffic
        (client /generate and the /v1 facade) never lands on a
        prefill specialist — it would answer 409 — and PREFILL-phase
        traffic (the disaggregated first hop) never lands on a
        decode specialist."""
        if phase == "prefill":
            return rep.role in ("prefill", "both")
        return rep.role in ("decode", "both")

    def _pickable(self, now, exclude=(), phase="decode"):
        return [r for r in self._replicas.values()
                if r.id not in exclude and self._serves(r, phase)
                and self._eligible(r, now)]

    @staticmethod
    def _prompt_row(raw):
        """The single prompt row of a /generate body as an int list,
        or None when the body is not topology-routable (multi-row,
        non-token prompt, malformed — those keep the affinity
        path)."""
        try:
            body = json.loads(raw.decode() or "{}")
        except Exception:
            return None
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            return None
        if isinstance(prompt[0], list):
            if len(prompt) != 1:
                return None  # batch rows share one replica anyway
            row = prompt[0]
        else:
            row = prompt
        if not row or not all(
                isinstance(t, int) and not isinstance(t, bool)
                for t in row):
            return None
        return row

    @staticmethod
    def _match_depth(rep, row, memo):
        """How many leading block chunks of prompt ``row`` the
        replica advertises as resident (device trie + host tier).
        ``memo`` caches the prompt's digests per block size across
        one request's replica comparisons.  A digest is a 32-bit
        HINT — the replica re-verifies tokens on admission, so an
        overcount here costs a miss, never wrong KV."""
        if not rep.prefix_digests:
            return 0
        bs = (rep.last_metrics or {}).get("kv_block_size")
        if not bs:
            return 0
        bs = int(bs)
        digs = memo.get(bs)
        if digs is None:
            digs = memo[bs] = chunk_digests(row, bs)
        n = 0
        for d in digs:
            if d not in rep.prefix_digests:
                break
            n += 1
        return n

    def _pick(self, affinity, now, exclude=(), phase="decode",
              row=None, memo=None):
        """Choose the attempt's replica: a half-open breaker's probe
        first (recovery must not wait for idle), then the replica
        advertising the longest resident prefix of ``row`` (when
        prefix routing supplied one), then the affinity target, then
        least-outstanding (ties by id for determinism)."""
        candidates = self._pickable(now, exclude, phase)
        if not candidates:
            return None
        half = [r for r in candidates if r.breaker == "half_open"]
        if half:
            rep = min(half, key=lambda r: r.id)
            rep.probing = True
            return rep
        if row is not None:
            warm = min(candidates,
                       key=lambda r: (-self._match_depth(r, row, memo),
                                      r.outstanding, r.id))
            if self._match_depth(warm, row, memo) > 0:
                return warm
        if affinity is not None:
            # rendezvous hash over the FULL registry (stable under
            # breaker flaps), honored only when the owner is eligible
            owner = max(
                self._replicas.values(),
                key=lambda r: zlib.crc32(
                    ("%s|%s" % (affinity, r.id)).encode()))
            if owner in candidates:
                return owner
        return min(candidates, key=lambda r: (r.outstanding, r.id))

    def _breaker_to(self, rep, state):
        if rep.breaker == state:
            return
        rep.breaker = state
        rep.probing = False
        if state == "open":
            rep.opened_at = time.monotonic()
        self.stats.record_breaker(rep.id, state)
        self.info("replica %s breaker -> %s", rep.id, state)

    def _breaker_failure(self, rep):
        rep.failures += 1
        rep.probing = False
        if rep.breaker == "half_open" \
                or rep.failures >= self.breaker_failures:
            self._breaker_to(rep, "open")

    def _breaker_success(self, rep):
        if rep.breaker == "open":
            # a stale success from an attempt launched BEFORE the
            # trip: the documented machine leaves `open` only via
            # cooldown → half_open probe, so a late reply must not
            # short-circuit recovery (it proves the replica was
            # alive THEN, not that it recovered)
            return
        rep.failures = 0
        if rep.breaker != "closed":
            self._breaker_to(rep, "closed")

    def _backoff(self, attempt):
        """Delay before retry ``attempt`` (1-based): exponential from
        ``retry_delay``, capped at ``retry_cap``, half-window jitter
        (the coordinator reconnect shape — fleet retries must
        decorrelate)."""
        base = min(self.retry_cap,
                   self.retry_delay * (2 ** (attempt - 1)))
        return base * (0.5 + 0.5 * random.random())

    def _inspect(self, raw, headers):
        """(idempotent, affinity_key, stream, cls) for a forwarded
        body (/generate and the /v1 facade).  Greedy and seed-pinned
        requests are idempotent (any replica answers the same
        tokens; embeddings/classify always are); the affinity key is
        the session header or the first ``affinity_tokens`` prompt
        tokens; ``stream`` marks SSE bodies for the pinning proxy;
        ``cls`` is the priority class name (SLO accounting — the
        replica still authoritatively validates it)."""
        try:
            body = json.loads(raw.decode() or "{}")
            prompt = body.get("prompt")
            if prompt is None:
                prompt = body.get("input")
        except Exception:
            return False, None, False, "normal"  # replica will 400 it
        idempotent = not float(body.get("temperature") or 0.0) \
            or body.get("seed") is not None
        affinity = headers.get("x-veles-session")
        if affinity is None and self.affinity_tokens > 0 \
                and isinstance(prompt, list) and prompt:
            row = prompt[0] if isinstance(prompt[0], list) else prompt
            affinity = repr(row[:self.affinity_tokens])
        prio = body.get("priority")
        if isinstance(prio, int) and not isinstance(prio, bool) \
                and 0 <= prio <= 2:
            cls = ("low", "normal", "high")[prio]
        elif isinstance(prio, str) \
                and prio.lower() in ("low", "normal", "high"):
            cls = prio.lower()
        else:
            cls = "normal"
        return idempotent, affinity, bool(body.get("stream")), cls

    async def _attempt(self, rep, raw, headers, timeout,
                       path="/generate", method="POST", trace=None,
                       attempt=0, hedge=False):
        """One forward, normalized to an :class:`_Outcome`, with the
        breaker/metrics accounting applied.  Each attempt — retries
        and hedges alike — is its OWN child span (``router.attempt``
        begin/end pair carrying the trace id, attempt number and
        replica), so the merged Chrome trace shows exactly which
        replica each leg of a retried request ran on."""
        async def _payload():
            # executor: an armed hang/delay stalls this attempt (and
            # times out below like any straggler), not the event loop
            dropped = await asyncio.get_running_loop() \
                .run_in_executor(None, faults.fire,
                                 "router.forward", rep.id)
            if dropped:
                raise ConnectionError("injected forward drop")
            return await self._http(
                rep, method, path,
                raw if method == "POST" else None,
                {k: v for k, v in headers.items()
                 if k in ("x-veles-session", "x-veles-trace",
                          "x-veles-tenant")})

        span = None
        if self._tron and trace is not None:
            span = next_span_id()
            events.record("router.attempt", "begin", cls="Router",
                          span=span, trace=trace, attempt=attempt,
                          replica=rep.id, hedge=hedge)
        t0 = time.monotonic()
        rep.outstanding += 1
        rep.requests += 1
        try:
            try:
                status, rheaders, rbody = await asyncio.wait_for(
                    _payload(), timeout)
                out = _Outcome(rep, status, rheaders, rbody)
            except faults.InjectedHTTPError as e:
                # a replica that REPLIES an error (http_error action)
                out = _Outcome(rep, e.status, {}, json.dumps(
                    {"error": {"code": e.status, "message": str(e),
                               "injected": True,
                               "trace_id": trace}}).encode())
            except asyncio.CancelledError:
                if span is not None:
                    events.record("router.attempt", "end",
                                  cls="Router", span=span,
                                  trace=trace, attempt=attempt,
                                  replica=rep.id, hedge=hedge,
                                  duration=time.monotonic() - t0,
                                  outcome="cancelled")
                raise
            except Exception as e:
                out = _Outcome(rep, error=e)
        finally:
            rep.outstanding -= 1
        if span is not None:
            events.record("router.attempt", "end", cls="Router",
                          span=span, trace=trace, attempt=attempt,
                          replica=rep.id, hedge=hedge,
                          duration=time.monotonic() - t0,
                          status=out.status,
                          outcome="ok" if out.error is None
                          else type(out.error).__name__)
        now = time.monotonic()
        if out.error is not None \
                or (out.status >= 500 and out.status != 503):
            self._breaker_failure(rep)
        else:
            # any reply proves liveness — 503 is backpressure, not a
            # fault; park the replica for its Retry-After instead
            self._breaker_success(rep)
            if out.status == 503:
                try:
                    after = float(out.headers.get("retry-after", 1))
                except ValueError:
                    after = 1.0
                rep.saturated_until = now + min(after, 5.0)
        self.stats.record_forward(rep.id, out.deliverable,
                                  tenant=headers.get(
                                      "x-veles-tenant"))
        return out

    async def _attempt_hedged(self, rep, raw, headers, timeout,
                              idempotent, now, path="/generate",
                              method="POST", trace=None, attempt=0):
        """The primary attempt, hedged once against a second replica
        when the primary straggles past ``hedge_delay`` and the
        request is idempotent.  Returns the winning outcome (a
        deliverable one when either attempt produced it)."""
        primary = asyncio.ensure_future(
            self._attempt(rep, raw, headers, timeout, path=path,
                          method=method, trace=trace,
                          attempt=attempt))
        if not idempotent or self.hedge_delay <= 0 \
                or not self._pickable(now, exclude=(rep.id,)):
            return await primary
        done, _ = await asyncio.wait({primary},
                                     timeout=self.hedge_delay)
        if primary in done:
            return primary.result()
        rep2 = self._pick(None, time.monotonic(),
                          exclude=(rep.id,))
        if rep2 is None:
            return await primary
        self.stats.record_hedge()
        hedge = asyncio.ensure_future(
            self._attempt(rep2, raw, headers, timeout, path=path,
                          method=method, trace=trace,
                          attempt=attempt, hedge=True))
        pending = {primary, hedge}
        best = None
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED)
            for task in done:
                out = task.result()
                if out.deliverable:
                    for p in pending:
                        p.cancel()
                    if task is hedge:
                        self.stats.record_hedge_win()
                    return out
                best = out
        return best

    async def _forward_request(self, path, raw, headers,
                               method="POST", trace=None):
        """The data-plane path (non-streaming): pick → attempt
        (hedged) → classify → retry/shed, all bounded by the request
        deadline.  The whole routed request is a ``router.request``
        span parenting one ``router.attempt`` span per try, and it
        sits in the live in-flight table (``GET /debug/requests``)
        until answered."""
        t0 = time.monotonic()
        deadline = t0 + self.request_timeout
        idempotent, affinity, _, cls = self._inspect(raw, headers)
        tenant = headers.get("x-veles-tenant")
        if method == "GET":
            idempotent = True
        root_span = None
        if self._tron and trace is not None:
            root_span = next_span_id()
            events.record("router.request", "begin", cls="Router",
                          span=root_span, trace=trace, path=path,
                          tenant=tenant)
        seq = next(self._req_seq)
        info = {"trace": trace, "path": path, "t0": t0,
                "attempts": 0, "replica": None, "stream": False,
                "cls": cls, "tenant": tenant}
        self._inflight[seq] = info
        # cache-topology routing: only single-row token /generate
        # bodies carry a routable prefix; everything else keeps the
        # affinity path untouched
        row = self._prompt_row(raw) if self.prefix_routing \
            and method == "POST" and path == "/generate" else None
        try:
            return await self._forward_attempts(
                path, raw, headers, method, trace, t0, deadline,
                idempotent, affinity, cls, info, row=row)
        finally:
            self._inflight.pop(seq, None)
            if root_span is not None:
                events.record("router.request", "end", cls="Router",
                              span=root_span, trace=trace, path=path,
                              tenant=tenant,
                              duration=time.monotonic() - t0,
                              attempts=info["attempts"])

    async def _forward_attempts(self, path, raw, headers, method,
                                trace, t0, deadline, idempotent,
                                affinity, cls, info, row=None):
        best_tokens = None
        last = None
        attempts = 0
        memo = {}
        while attempts < self.retries:
            now = time.monotonic()
            if now >= deadline:
                break
            rep = self._pick(affinity, now, row=row, memo=memo)
            if rep is None:
                break  # fleet-level shed (or nothing left to try)
            attempts += 1
            info["attempts"] = attempts
            info["replica"] = rep.id
            if attempts > 1:
                self.stats.record_retry()
            elif row is not None and self.prefix_fetch:
                # first attempt only: ship a peer's longer resident
                # prefix onto the chosen replica before forwarding
                # (best-effort — a failed fetch just admits cold)
                await self._maybe_prefix_fetch(
                    rep, row, memo, trace, deadline)
            out = await self._attempt_hedged(
                rep, raw, headers, deadline - now, idempotent, now,
                path=path, method=method, trace=trace,
                attempt=attempts)
            if out.deliverable:
                self.stats.record_request(
                    (time.monotonic() - t0) * 1e3, cls=cls)
                rheaders = {
                    "Content-Type": out.headers.get(
                        "content-type", "application/json"),
                    "X-Veles-Router-Attempts": str(attempts)}
                if trace is not None:
                    rheaders["X-Veles-Trace"] = trace
                if "x-veles-replica" in out.headers:
                    rheaders["X-Veles-Replica"] = \
                        out.headers["x-veles-replica"]
                else:
                    rheaders["X-Veles-Replica"] = out.rep.id
                if "retry-after" in out.headers:
                    rheaders["Retry-After"] = \
                        out.headers["retry-after"]
                return out.status, rheaders, out.body
            last = out
            toks = out.tokens_generated()
            if toks is not None:
                best_tokens = max(best_tokens or 0, toks)
            delay = self._backoff(attempts)
            if time.monotonic() + delay >= deadline:
                break
            await asyncio.sleep(delay)
        # every attempt failed (or none was possible) — shed/report
        self.stats.record_request((time.monotonic() - t0) * 1e3,
                                  cls=cls)
        if last is None:
            self.stats.record_shed()
            return self._error(
                503, "no eligible replica (fleet saturated, "
                "draining or open)", retry_after=self.shed_retry_after,
                attempts=attempts, shed=True, trace=trace)
        if last.error is not None:
            return self._error(
                502, "replica unreachable after %d attempt(s): %s"
                % (attempts, last.error), attempts=attempts,
                tokens_generated=best_tokens, trace=trace)
        return self._error(
            last.status, "replica error after %d attempt(s)"
            % attempts,
            retry_after=self.shed_retry_after
            if last.status == 503 else None,
            attempts=attempts, tokens_generated=best_tokens,
            trace=trace)

    async def _http_begin(self, rep, method, path, body,
                          headers=None):
        """Open a replica request and return after the response
        HEADERS arrive, leaving the body unread on the connection —
        the streaming proxy's handle: ``(reader, writer, status,
        rheaders)``.  The caller owns closing the writer."""
        reader, writer = await asyncio.open_connection(rep.host,
                                                       rep.port)
        try:
            blob = body if body is not None else b""
            lines = ["%s %s HTTP/1.1" % (method, path),
                     "Host: %s:%d" % (rep.host, rep.port),
                     "Connection: close",
                     "Content-Length: %d" % len(blob)]
            if not any(k.lower() == "content-type"
                       for k in (headers or {})):
                lines.append("Content-Type: application/json")
            for k, v in (headers or {}).items():
                lines.append("%s: %s" % (k, v))
            writer.write(("\r\n".join(lines) + "\r\n\r\n").encode()
                         + blob)
            await writer.drain()
            line = (await reader.readline()).decode("latin-1")
            parts = line.split(None, 2)
            if len(parts) < 2 or not parts[1].isdigit():
                raise ConnectionError("bad status line %r" % line)
            status = int(parts[1])
            rheaders = {}
            while True:
                hline = await reader.readline()
                if hline in (b"\r\n", b"\n", b""):
                    break
                key, _, val = hline.decode("latin-1").partition(":")
                rheaders[key.strip().lower()] = val.strip()
            return reader, writer, status, rheaders
        except BaseException:
            writer.close()
            raise

    async def _stream_proxy(self, path, headers, raw, writer,
                            trace=None):
        """Proxy one streaming (SSE) request frame by frame.

        Retries, backoff and replica selection apply freely UNTIL a
        replica's response status line arrives; the first forwarded
        byte pins the client's response headers.  For REPLAYABLE
        ``/generate`` streams (single row, greedy or seed-pinned —
        the idempotent set) the pin is no longer final: the router
        records the request's replay state (body + every token frame
        it forwarded) and when the pinned replica dies or errors
        mid-stream it RESUBMITS the request to another eligible
        replica through the ``resume_tokens`` lane — the replica
        re-prefills prompt + forwarded prefix and continues sampling
        at draw counter ``len(forwarded)``, so the spliced
        continuation is bit-identical to an uninterrupted run
        (fp32; the PR 7 preempt→resume contract) and the client sees
        zero error frames.  Non-replayable streams (multi-row,
        unseeded sampling, the /v1 facade) keep the old pin-and-
        truncate contract.  Hedging never arms for streams.  A
        mid-stream client disconnect closes the upstream connection
        — including a resume leg in flight — which makes the
        replica's SSE writer fail and CANCEL the request (slot + KV
        blocks free at the next decode boundary).  Error replies
        (shed 503s, 4xx) stay ordinary JSON — only a success opens
        the event stream."""
        t0 = time.monotonic()
        deadline = t0 + self.request_timeout
        _, affinity, _, cls = self._inspect(raw, headers)
        tenant = headers.get("x-veles-tenant")
        fwd = {k: v for k, v in headers.items()
               if k in ("x-veles-session", "x-veles-trace",
                        "x-veles-tenant")}
        root_span = None
        if self._tron and trace is not None:
            root_span = next_span_id()
            events.record("router.request", "begin", cls="Router",
                          span=root_span, trace=trace, path=path,
                          stream=True, tenant=tenant)
        seq = next(self._req_seq)
        info = {"trace": trace, "path": path, "t0": t0,
                "attempts": 0, "replica": None, "stream": True,
                "cls": cls, "tenant": tenant}
        self._inflight[seq] = info
        try:
            await self._stream_attempts(
                path, raw, writer, trace, t0, deadline, affinity,
                cls, fwd, info)
        finally:
            self._inflight.pop(seq, None)
            if root_span is not None:
                events.record("router.request", "end", cls="Router",
                              span=root_span, trace=trace, path=path,
                              stream=True, tenant=tenant,
                              duration=time.monotonic() - t0,
                              attempts=info["attempts"])

    #: SSE frame terminator — the replica's sse_event wire format
    #: (``data: <json>\n\n``); the failover parser splits on it
    _SSE_SEP = b"\n\n"

    def _stream_replay_state(self, path, raw):
        """Replay state for mid-stream failover, or None when the
        stream is not resumable: only single-row ``/generate``
        bodies that are IDEMPOTENT (greedy, or seed-pinned sampling
        — any replica regenerates the same tokens) and not already a
        resume leg qualify.  ``generated`` accumulates every token
        frame the router has forwarded; a resume resubmits the body
        with exactly that prefix."""
        if path != "/generate":
            return None
        try:
            body = json.loads(raw.decode() or "{}")
        except Exception:
            return None
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not prompt \
                or isinstance(prompt[0], list) \
                or body.get("beam") or body.get("resume_tokens"):
            return None
        if float(body.get("temperature") or 0.0) \
                and body.get("seed") is None:
            return None      # unseeded sampling cannot be replayed
        try:
            if int(body.get("steps") or 0) < 1:
                return None
        except (TypeError, ValueError):
            return None
        return {"body": body, "generated": []}

    async def _resume_begin(self, rep, state, fwd, timeout):
        """Open one resume leg: the replay body + the forwarded
        prefix through the replica's loopback/admin
        ``resume_tokens`` lane (the admin bearer rides along for
        remote replicas).  Returns the ``_http_begin`` handle."""
        body = dict(state["body"])
        body["stream"] = True
        body["resume_tokens"] = list(state["generated"])
        headers = dict(fwd)
        from veles_tpu.config import root
        token = root.common.api.get("admin_token", None)
        if token:
            headers["Authorization"] = "Bearer %s" % token
        return await asyncio.wait_for(
            self._http_begin(rep, "POST", "/generate",
                             json.dumps(body).encode(), headers),
            timeout)

    async def _relay_one_frame(self, rep, frame, writer, state):
        """Forward one complete SSE frame to the client, tracking
        replay state.  Returns None to keep relaying, ``"done"``
        after the terminal [DONE], ``"died"`` when the frame is an
        error frame (failover material — NOT forwarded) or the armed
        ``router.stream.replica_death`` point killed the replica
        under this frame, ``"client_gone"`` when the client hung
        up."""
        data = frame.strip()
        if data.startswith(b"data:"):
            data = data[5:].strip()
        payload = None
        if data != b"[DONE]":
            try:
                payload = json.loads(data.decode())
            except Exception:
                payload = None
        is_token = isinstance(payload, dict) and "token" in payload
        if isinstance(payload, dict) and "error" in payload:
            # a mid-stream scheduler failure (watchdog, close, the
            # replica dying politely) — resume elsewhere instead of
            # delivering the error frame
            return "died"
        if is_token:
            # the chaos hook: an armed drop/exception here IS the
            # pinned replica dying before this frame reached the
            # client — the token is not counted as forwarded, so the
            # resume regenerates it
            try:
                dropped = await asyncio.get_running_loop() \
                    .run_in_executor(None, faults.fire,
                                     "router.stream.replica_death",
                                     rep.id)
            except faults.InjectedFault:
                return "died"
            if dropped:
                return "died"
        try:
            writer.write(frame)
            await writer.drain()
        except (ConnectionError, OSError):
            return "client_gone"
        if is_token and state is not None:
            state["generated"].append(int(payload["token"]))
        return "done" if data == b"[DONE]" else None

    async def _relay_sse_frames(self, rep, upstream, writer, state,
                                deadline):
        """Relay one pinned upstream's SSE stream frame by frame.
        Returns ``"done"`` (terminal [DONE] delivered), ``"died"``
        (upstream EOF/error/error-frame before [DONE] — failover
        material), ``"client_gone"`` or ``"deadline"``.  A trailing
        partial frame is never forwarded, so the replay state counts
        exactly the frames the client received."""
        buf = b""
        while True:
            try:
                chunk = await asyncio.wait_for(
                    upstream.read(4096),
                    max(0.05, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                return "deadline"
            except (ConnectionError, asyncio.IncompleteReadError,
                    OSError):
                return "died"
            if not chunk:
                return "died"   # EOF without [DONE]: replica died
            buf += chunk
            while self._SSE_SEP in buf:
                frame, buf = buf.split(self._SSE_SEP, 1)
                verdict = await self._relay_one_frame(
                    rep, frame + self._SSE_SEP, writer, state)
                if verdict is not None:
                    return verdict

    async def _relay_blind(self, upstream, writer, deadline):
        """The legacy pin-and-truncate relay for non-resumable
        streams (and non-200 bodies): bytes through as they arrive
        until EOF, client disconnect or the deadline."""
        try:
            while True:
                chunk = await asyncio.wait_for(
                    upstream.read(4096),
                    max(1.0, deadline - time.monotonic()))
                if not chunk:
                    break
                writer.write(chunk)
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError,
                asyncio.TimeoutError):
            # client gone or replica stalled past the deadline: drop
            # the upstream connection — the replica's SSE writer
            # fails and cancels the request, freeing slot + blocks
            pass

    async def _stream_attempts(self, path, raw, writer, trace, t0,
                               deadline, affinity, cls, fwd, info):
        state = self._stream_replay_state(path, raw)
        attempts = 0
        last_status, last_body = None, b""
        pinned = False       # the client's SSE headers are out
        exclude = set()      # replicas that died under THIS stream
        try:
            while attempts < self.retries:
                now = time.monotonic()
                if now >= deadline:
                    break
                rep = self._pick(affinity, now,
                                 exclude=tuple(exclude))
                if rep is None:
                    break
                attempts += 1
                info["attempts"] = attempts
                info["replica"] = rep.id
                if attempts > 1 and not pinned:
                    self.stats.record_retry()
                kind, arg = await self._stream_one_attempt(
                    path, raw, writer, trace, deadline, fwd, rep,
                    attempts, pinned, state)
                if kind == "retry":
                    if arg is not None:
                        last_status, last_body = arg
                    if pinned:
                        # a failed RESUME leg: this replica cannot
                        # continue the stream right now
                        exclude.add(rep.id)
                    continue
                if kind == "sent":
                    # non-resumable relay (or error body) delivered
                    self.stats.record_request(
                        (time.monotonic() - t0) * 1e3, cls=cls)
                    return
                if kind == "relay":
                    # ("resumed" is recorded inside the attempt, at
                    # the moment a resume leg's 200 arrives — before
                    # its first spliced frame reaches the client)
                    pinned = True
                    if arg == "done":
                        self.stats.record_request(
                            (time.monotonic() - t0) * 1e3, cls=cls)
                        return
                    if arg == "client_gone":
                        # the client hung up (possibly mid-failover):
                        # the attempt's upstream was closed by the
                        # per-attempt cleanup, cancelling the request
                        # replica-side — nothing left to resume for
                        if exclude:
                            self.stats.record_stream_failover(
                                "abandoned")
                        self.stats.record_request(
                            (time.monotonic() - t0) * 1e3, cls=cls)
                        return
                    if arg == "deadline":
                        break
                    # arg == "died": the pinned replica is gone —
                    # the loop resumes on another one
                    exclude.add(rep.id)
        except asyncio.CancelledError:
            raise
        if pinned:
            # the stream started but could not complete and no
            # replica can continue it: end it with ONE structured
            # error frame + [DONE] instead of a silent truncation
            if exclude:   # a replica death was involved, not just
                self.stats.record_stream_failover("failed")  # expiry
            self.stats.record_request((time.monotonic() - t0) * 1e3,
                                      cls=cls)
            err = {"error": {
                "code": 503,
                "message": "stream interrupted and no eligible "
                           "replica could resume it",
                "trace_id": trace,
                "tokens_generated": len(state["generated"])
                if state else None}}
            try:
                writer.write(b"data: " + json.dumps(
                    err, separators=(",", ":")).encode()
                    + b"\n\ndata: [DONE]\n\n")
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            return
        # no replica ever produced a status line (or only 5xx) — shed
        self.stats.record_request((time.monotonic() - t0) * 1e3,
                                  cls=cls)
        if last_status is not None:
            status, rheaders, rbody = self._error(
                last_status, "replica error after %d attempt(s)"
                % attempts, attempts=attempts, trace=trace)
        else:
            self.stats.record_shed()
            status, rheaders, rbody = self._error(
                503, "no eligible replica (fleet saturated, "
                "draining or open)",
                retry_after=self.shed_retry_after,
                attempts=attempts, shed=True, trace=trace)
        out = ["HTTP/1.1 %d X" % status, "Connection: close",
               "Content-Length: %d" % len(rbody)]
        out += ["%s: %s" % (k, v) for k, v in rheaders.items()]
        writer.write(("\r\n".join(out) + "\r\n\r\n").encode()
                     + rbody)
        await writer.drain()

    async def _stream_one_attempt(self, path, raw, writer, trace,
                                  deadline, fwd, rep, attempts,
                                  pinned, state):
        """One streaming forward attempt (first leg or resume leg),
        with the breaker/metrics accounting.  Returns a verdict
        tuple: ``("retry", (status, body) | None)`` to try another
        replica, ``("sent", None)`` when a complete non-resumable
        reply was delivered, or ``("relay", outcome)`` with the
        frame-relay outcome of a pinned resumable stream."""
        now = time.monotonic()
        span = None
        if self._tron and trace is not None:
            span = next_span_id()
            events.record("router.attempt", "begin", cls="Router",
                          span=span, trace=trace, attempt=attempts,
                          replica=rep.id, stream=True, resume=pinned)
        t_att = time.monotonic()
        rep.outstanding += 1
        rep.requests += 1
        upstream = up_writer = None
        injected_body = None
        try:
            try:
                dropped = await asyncio.get_running_loop() \
                    .run_in_executor(None, faults.fire,
                                     "router.forward", rep.id)
                if dropped:
                    raise ConnectionError("injected forward drop")
                if pinned:
                    upstream, up_writer, status, rheaders = \
                        await self._resume_begin(
                            rep, state, fwd, deadline - now)
                else:
                    upstream, up_writer, status, rheaders = \
                        await asyncio.wait_for(
                            self._http_begin(rep, "POST", path, raw,
                                             fwd),
                            deadline - now)
            except faults.InjectedHTTPError as e:
                status = e.status
                rheaders = {"content-type": "application/json"}
                injected_body = json.dumps(
                    {"error": {"code": status,
                               "message": str(e),
                               "injected": True,
                               "trace_id": trace}}).encode()
                upstream = None
            except asyncio.CancelledError:
                raise
            except Exception:
                self._breaker_failure(rep)
                self.stats.record_forward(
                    rep.id, False, tenant=fwd.get("x-veles-tenant"))
                return ("retry", (502, b""))
            if status >= 500 and status != 503:
                self._breaker_failure(rep)
                self.stats.record_forward(
                    rep.id, False, tenant=fwd.get("x-veles-tenant"))
                body = b""
                if upstream is not None:
                    try:
                        body = await asyncio.wait_for(
                            upstream.read(65536), 5.0)
                    except Exception:
                        body = b""
                return ("retry", (status, body))
            # the replica spoke: liveness proven (503 included)
            self._breaker_success(rep)
            self.stats.record_forward(
                rep.id, True, tenant=fwd.get("x-veles-tenant"))
            if status == 503:
                try:
                    after = float(rheaders.get("retry-after", 1))
                except ValueError:
                    after = 1.0
                rep.saturated_until = now + min(after, 5.0)
            if pinned:
                # resume legs can only relay a 200 event stream —
                # the client's headers are long gone; anything else
                # is a failed resume attempt
                if status != 200 or upstream is None:
                    return ("retry", None)
                # recorded BEFORE the continuation's first frame, so
                # the count is visible by the time the client reads
                # the spliced [DONE]
                self.stats.record_stream_failover("resumed")
                outcome = await self._relay_sse_frames(
                    rep, upstream, writer, state, deadline)
                return ("relay", outcome)
            # FIRST reply: pin the client response — headers out,
            # then frames/bytes as they arrive (SSE for a 200, the
            # structured JSON error body otherwise).  One client
            # stream counts ONE pin, resume legs never re-count.
            self.stats.record_stream(rep.id)
            out = ["HTTP/1.1 %d %s" % (status, "OK"
                                       if status == 200 else "X"),
                   "Connection: close",
                   "Content-Type: %s" % rheaders.get(
                       "content-type", "application/json"),
                   "X-Veles-Router-Attempts: %d" % attempts,
                   "X-Veles-Replica: %s" % rheaders.get(
                       "x-veles-replica", rep.id)]
            if trace is not None:
                out.append("X-Veles-Trace: %s" % trace)
            if "content-length" in rheaders:
                out.append("Content-Length: %s"
                           % rheaders["content-length"])
            if "retry-after" in rheaders:
                out.append("Retry-After: %s"
                           % rheaders["retry-after"])
            writer.write(("\r\n".join(out) + "\r\n\r\n").encode())
            if upstream is None:       # injected reply, no socket
                try:
                    writer.write(injected_body or b"")
                    await writer.drain()
                except (ConnectionError, OSError):
                    pass
                return ("sent", None)
            if status == 200 and state is not None:
                outcome = await self._relay_sse_frames(
                    rep, upstream, writer, state, deadline)
                return ("relay", outcome)
            await self._relay_blind(upstream, writer, deadline)
            return ("sent", None)
        finally:
            rep.outstanding -= 1
            if up_writer is not None:
                up_writer.close()
            if span is not None:
                events.record(
                    "router.attempt", "end", cls="Router",
                    span=span, trace=trace, attempt=attempts,
                    replica=rep.id, stream=True, resume=pinned,
                    duration=time.monotonic() - t_att)

    # -- live in-flight inspection ---------------------------------------

    def _inflight_rows(self):
        """The router-side in-flight table: one row per request the
        router is still proxying (trace id, path, age, attempt count,
        current replica, streaming flag) — the router half of ``GET
        /debug/requests``.  Loop thread only."""
        now = time.monotonic()
        return [{
            "trace": info["trace"], "phase": "proxy",
            "path": info["path"],
            "age_s": round(now - info["t0"], 3),
            "attempts": info["attempts"],
            "replica": info["replica"],
            "stream": info["stream"], "cls": info["cls"],
            "tenant": info.get("tenant"),
        } for info in self._inflight.values()]

    def debug_requests(self, timeout=2.0):
        """Thread-safe snapshot of :meth:`_inflight_rows` (the
        flight-recorder registry calls this from whatever thread is
        dumping; a dead/stuck loop answers [] instead of hanging the
        crash path)."""
        with self._lock:
            loop = self._loop
        if loop is None:
            return []

        async def _rows():
            return self._inflight_rows()
        try:
            return asyncio.run_coroutine_threadsafe(
                _rows(), loop).result(timeout)
        except Exception:
            return []

    # -- health polling --------------------------------------------------

    async def _health_loop(self):
        while True:
            await asyncio.sleep(self.health_interval)
            reps = list(self._replicas.values())
            if reps:
                await asyncio.gather(
                    *[self._probe(r) for r in reps],
                    return_exceptions=True)

    async def _probe(self, rep):
        try:
            dropped = await asyncio.get_running_loop() \
                .run_in_executor(None, faults.fire,
                                 "router.replica.health", rep.id)
            if dropped:
                raise ConnectionError("injected health drop")
            status, _, body = await asyncio.wait_for(
                self._http(rep, "GET", "/healthz", None),
                self.health_timeout)
            info = json.loads(body.decode())
        except asyncio.CancelledError:
            raise
        except Exception:
            # flappy/unreachable: two strikes take it out of rotation
            # (health exclusion, NOT a breaker trip)
            rep.health_failures += 1
            if rep.health_failures >= 2:
                if rep.healthy:
                    self.info("replica %s unreachable — out of "
                              "rotation", rep.id)
                rep.healthy = False
                rep.status = "unreachable"
                # the cached exposition text is stale the moment the
                # replica is unreachable: without this the federated
                # merge keeps summing a DEAD replica's final counters
                # until something else overwrites last_scrape
                rep.scrape_failed = True
                self.stats.record_replica_up(rep.id, False)
            return
        rep.health_failures = 0
        self.stats.record_replica_up(rep.id, True)
        rep.last_health = info
        rep.role = str(info.get("role") or "both")
        rep.status = str(info.get("status", "unknown"))
        rep.draining = rep.marked_draining \
            or rep.status == "draining" \
            or bool(info.get("draining"))
        # a draining replica is ALIVE (it finishes its in-flight
        # work); "halted" (health policy latched) is not servable
        rep.healthy = status == 200 or rep.draining
        try:
            _, _, mbody = await asyncio.wait_for(
                self._http(rep, "GET", "/serving/metrics", None),
                self.health_timeout)
            rep.last_metrics = json.loads(mbody.decode())
            digs = rep.last_metrics.get("prefix_digests")
            rep.prefix_digests = frozenset(
                int(d) for d in digs) if isinstance(digs, list) \
                else frozenset()
        except asyncio.CancelledError:
            raise
        except Exception:
            pass
        # federation scrape piggybacks the same poll: the replica's
        # Prometheus text rides into GET /metrics/fleet's merge
        try:
            status, _, sbody = await asyncio.wait_for(
                self._http(rep, "GET", "/metrics", None),
                self.health_timeout)
            if status == 200:
                rep.last_scrape = sbody.decode("utf-8", "replace")
                rep.scrape_failed = False
            else:
                rep.scrape_failed = True
        except asyncio.CancelledError:
            raise
        except Exception:
            rep.scrape_failed = True

    # -- plumbing: async HTTP client + server ----------------------------

    async def _http(self, rep, method, path, body, headers=None):
        reader, writer = await asyncio.open_connection(rep.host,
                                                       rep.port)
        try:
            blob = body if body is not None else b""
            lines = ["%s %s HTTP/1.1" % (method, path),
                     "Host: %s:%d" % (rep.host, rep.port),
                     "Connection: close",
                     "Content-Length: %d" % len(blob)]
            # an explicit Content-Type (the binary KV wire) wins
            # over the JSON default — never send the header twice
            if body is not None and not any(
                    k.lower() == "content-type"
                    for k in (headers or {})):
                lines.append("Content-Type: application/json")
            for k, v in (headers or {}).items():
                lines.append("%s: %s" % (k, v))
            writer.write(("\r\n".join(lines) + "\r\n\r\n").encode()
                         + blob)
            await writer.drain()
            line = (await reader.readline()).decode("latin-1")
            parts = line.split(None, 2)
            if len(parts) < 2 or not parts[1].isdigit():
                raise ConnectionError("bad status line %r" % line)
            status = int(parts[1])
            rheaders = {}
            while True:
                hline = await reader.readline()
                if hline in (b"\r\n", b"\n", b""):
                    break
                key, _, val = hline.decode("latin-1").partition(":")
                rheaders[key.strip().lower()] = val.strip()
            length = rheaders.get("content-length")
            if length is not None:
                rbody = await reader.readexactly(int(length))
            else:
                rbody = await reader.read()
            return status, rheaders, rbody
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    def _error(self, code, message, retry_after=None, trace=None,
               **extra):
        """Structured error reply; ``trace`` rides the body as
        ``trace_id`` AND the ``X-Veles-Trace`` header, so a failed or
        slow request is correlatable from the client side (the
        ``attempts`` extra says how many replicas were tried)."""
        err = {"code": int(code), "message": str(message)}
        if trace is not None:
            err["trace_id"] = trace
        err.update({k: v for k, v in extra.items() if v is not None})
        headers = {"Content-Type": "application/json"}
        if trace is not None:
            headers["X-Veles-Trace"] = trace
        if retry_after is not None:
            headers["Retry-After"] = str(max(1, int(retry_after)))
        return int(code), headers, json.dumps({"error": err}).encode()

    #: POST paths proxied to the replicas (streaming bodies divert
    #: to the pinning proxy in _serve_conn)
    FORWARD_POSTS = ("/generate", "/v1/completions",
                     "/v1/embeddings", "/v1/classify")

    def _disagg_active(self, now):
        """Disaggregated dispatch engages only when SPECIALISTS of
        both phases exist and are eligible — a fleet of "both"
        replicas keeps the plain colocated path (zero behavior
        change for every pre-role deployment)."""
        reps = self._replicas.values()
        return any(r.role == "prefill" and self._eligible(r, now)
                   for r in reps) \
            and any(r.role == "decode" and self._eligible(r, now)
                    for r in reps)

    async def _maybe_disagg(self, raw, headers, trace):
        """Disaggregated /generate: prefill on a prefill-specialist
        → fetch its KV export → hand the blocks to an
        affinity-picked decode replica for the token loop.  Every
        hop is individually retryable: a prefill specialist dying
        before its export was fetched re-runs prefill on ANOTHER
        specialist (the export is one-shot, so the fetch is never
        retried against a second owner), and a decode replica
        failing the import gets the SAME export payload retried on a
        peer.  Returns the final reply tuple, or None to fall back
        to the plain colocated forward (multi-row/stream/beam
        bodies, no specialists up, or every hop budget exhausted —
        the decode pool can always serve the request cold, so a
        request is NEVER failed while a colocated-capable replica
        exists)."""
        now = time.monotonic()
        if not self._disagg_active(now):
            return None
        try:
            body = json.loads(raw.decode() or "{}")
            prompt = body.get("prompt")
        except Exception:
            return None      # the replica will 400 it
        if not isinstance(prompt, list) or not prompt \
                or body.get("stream") or body.get("beam") \
                or body.get("resume_tokens") \
                or int(body.get("steps") or 0) < 1:
            return None
        squeeze = not isinstance(prompt[0], list)
        rows = [prompt] if squeeze else prompt
        if len(rows) != 1:
            return None      # batch bodies stay colocated
        deadline = now + self.request_timeout
        _, affinity, _, cls = self._inspect(raw, headers)
        pf_body = json.dumps({"prompt": rows[0],
                              "priority": body.get("priority")}) \
            .encode()
        export = None
        pre = None
        tried_pre = set()
        for _ in range(2):   # prefill+fetch: up to two specialists
            if time.monotonic() >= deadline:
                return None
            specialists = [
                r for r in self._pickable(time.monotonic(),
                                          exclude=tuple(tried_pre),
                                          phase="prefill")
                if r.role == "prefill"]
            if not specialists:
                return None  # no SPECIALIST free — serve colocated
            pre = min(specialists,
                      key=lambda r: (r.outstanding, r.id))
            tried_pre.add(pre.id)
            out = await self._attempt(
                pre, pf_body, headers, deadline - time.monotonic(),
                path="/serving/prefill", trace=trace)
            if not out.deliverable or out.status != 200:
                continue     # prefill failed: try the next owner
            try:
                handle = json.loads(out.body.decode())["handle"]
            except Exception:
                continue
            # THE chaos window: the specialist can die between
            # parking the export and our fetch — an armed drop/
            # exception here is exactly that death
            try:
                dropped = await asyncio.get_running_loop() \
                    .run_in_executor(None, faults.fire,
                                     "disagg.export.fetch", pre.id)
            except faults.InjectedFault:
                dropped = True
            if not dropped:
                out = await self._attempt(
                    pre, None, headers,
                    deadline - time.monotonic(),
                    path="/serving/kv_export/%s" % handle,
                    method="GET", trace=trace)
                if out.deliverable and out.status == 200:
                    try:
                        export = json.loads(out.body.decode())
                        break
                    except Exception:
                        export = None
            # the fetch failed (death, injected drop, expiry 404 or
            # a one-shot 409 race): the record is unrecoverable —
            # re-run prefill from the prompt on another specialist
        if export is None:
            return None
        imp_body = json.dumps({
            "export": export, "steps": body.get("steps"),
            "temperature": body.get("temperature"),
            "top_k": body.get("top_k"), "seed": body.get("seed"),
            "stop": body.get("stop"),
            "priority": body.get("priority")}).encode()
        tried_dec = {pre.id}
        for _ in range(2):   # import: up to two decode replicas —
            #                  the payload is router-held, so a dead
            #                  importer costs one retry, not a
            #                  re-prefill
            if time.monotonic() >= deadline:
                return None
            dec = self._pick(affinity, time.monotonic(),
                             exclude=tuple(tried_dec))
            if dec is None:
                return None
            tried_dec.add(dec.id)
            out = await self._attempt(
                dec, imp_body, headers,
                deadline - time.monotonic(),
                path="/serving/kv_import", trace=trace)
            if not out.deliverable or out.status != 200:
                continue
            try:
                toks = json.loads(out.body.decode())["tokens"]
            except Exception:
                continue
            self.stats.record_disagg()
            self.stats.record_request(
                (time.monotonic() - now) * 1e3, cls=cls)
            rheaders = {"Content-Type": "application/json",
                        "X-Veles-Router-Disagg": "%s>%s"
                        % (pre.id, dec.id),
                        "X-Veles-Replica": dec.id}
            if trace is not None:
                rheaders["X-Veles-Trace"] = trace
            return 200, rheaders, json.dumps(
                {"tokens": toks if squeeze else [toks]}).encode()
        return None

    async def _maybe_prefix_fetch(self, target, row, memo, trace,
                                  deadline):
        """Ship the prompt's warm prefix onto ``target`` before the
        forward: when a PEER advertises a resident prefix at least
        ``prefix_fetch_min`` blocks longer than the target's, fetch
        it over the binary KV wire (``POST /serving/prefix_export``
        on the peer, Accept ``application/x-veles-kv``) and import it
        into the target (``POST /serving/prefix_import``, same
        frame).  DRAINING peers still qualify as holders — a
        draining replica's cache is exactly the warmth worth
        rescuing, and its scheduler serves prefix exports to the
        end.  Best-effort throughout: every failed leg counts
        ``prefix_fetch_fails`` and the request proceeds cold; the
        second-best holder gets one retry.  Fault point
        ``router.prefix.fetch`` (keyed by the holder id) injects the
        peer dying between advertisement and fetch."""
        have = self._match_depth(target, row, memo)
        holders = [r for r in self._replicas.values()
                   if r.id != target.id and r.healthy
                   and self._match_depth(r, row, memo) - have
                   >= self.prefix_fetch_min]
        holders.sort(key=lambda r: (-self._match_depth(r, row, memo),
                                    r.outstanding, r.id))
        for holder in holders[:2]:
            budget = min(deadline - time.monotonic(), 10.0)
            if budget <= 0:
                return
            try:
                dropped = await asyncio.get_running_loop() \
                    .run_in_executor(None, faults.fire,
                                     "router.prefix.fetch", holder.id)
            except faults.InjectedFault:
                dropped = True
            blob = None
            if not dropped:
                try:
                    status, rheaders, body = await asyncio.wait_for(
                        self._http(
                            holder, "POST", "/serving/prefix_export",
                            json.dumps({"tokens": row}).encode(),
                            {"Accept": WIRE_CONTENT_TYPE}),
                        budget)
                    ctype = rheaders.get("content-type", "") \
                        .split(";")[0].strip().lower()
                    if status == 200 and ctype == WIRE_CONTENT_TYPE:
                        blob = body
                except asyncio.CancelledError:
                    raise
                except Exception:
                    blob = None
            if blob is None:
                # advertisement was stale (evicted since the poll),
                # the peer died, or the drop was injected — next
                self.stats.record_prefix_fetch_fail()
                continue
            budget = min(deadline - time.monotonic(), 10.0)
            if budget <= 0:
                return
            try:
                status, _, rbody = await asyncio.wait_for(
                    self._http(
                        target, "POST", "/serving/prefix_import",
                        blob, {"Content-Type": WIRE_CONTENT_TYPE}),
                    budget)
                if status == 200:
                    blocks = int(json.loads(
                        rbody.decode()).get("blocks") or 0)
                    self.stats.record_prefix_fetch(max(1, blocks))
                    self.info("prefix fetch %s -> %s: %d block(s)",
                              holder.id, target.id, blocks)
                    return
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            # the import leg failed (target busy/shape mismatch) —
            # a second holder's export rarely helps, but it is the
            # only remaining card and costs one bounded POST
            self.stats.record_prefix_fetch_fail()

    def _fleet_families(self):
        """loop thread: every replica's last-polled /metrics text
        merged (counters/histograms summed, gauges re-labeled per
        replica) + the veles_fleet_* rollups — the one federated
        view /metrics/fleet renders, the history store samples and
        /tenants/usage totals from."""
        from veles_tpu.telemetry import federation
        scrapes, errors = [], []
        for rep in self._replicas.values():
            if rep.last_scrape and not rep.scrape_failed:
                scrapes.append((rep.id, federation.parse_prometheus(
                    rep.last_scrape)))
            else:
                errors.append(rep.id)
        return federation.fleet_families(scrapes, errors=errors)

    async def _fleet_async(self):
        return self._fleet_families()

    _TENANT_USAGE_FAMILIES = {
        "veles_tenant_usage_prompt_tokens_total": "prompt_tokens",
        "veles_tenant_usage_generated_tokens_total":
            "generated_tokens",
        "veles_tenant_usage_kv_block_seconds_total":
            "kv_block_seconds",
        "veles_tenant_usage_compute_seconds_total":
            "compute_seconds",
    }

    def _tenant_usage(self, window=60.0):
        """loop thread: the ``GET /tenants/usage`` rollup — exact
        fleet-summed totals straight from the CURRENT federated
        merge (counters sum across replicas, so these equal the
        scheduler-side per-tenant counters exactly), plus windowed
        token rates answered by the history store."""
        totals = {}
        for fam in self._fleet_families():
            field = self._TENANT_USAGE_FAMILIES.get(fam["name"])
            if field is None:
                continue
            for suffix, labels, value in fam["samples"]:
                if suffix:
                    continue
                rec = totals.setdefault(
                    labels.get("tenant", "anon"),
                    {f: 0.0
                     for f in self._TENANT_USAGE_FAMILIES.values()})
                rec[field] += value
        out = {}
        for tenant, rec in sorted(totals.items()):
            row = {
                "prompt_tokens": int(rec["prompt_tokens"]),
                "generated_tokens": int(rec["generated_tokens"]),
                "kv_block_seconds": round(rec["kv_block_seconds"], 6),
                "compute_seconds": round(rec["compute_seconds"], 6),
            }
            if self.tsdb is not None:
                for field in ("prompt_tokens", "generated_tokens"):
                    rate = self.tsdb.range(
                        "veles_tenant_usage_%s_total" % field,
                        {"tenant": tenant}, window=window, agg="rate")
                    row["%s_per_sec" % field] = round(rate, 4) \
                        if rate is not None else None
            out[tenant] = row
        return {"window_s": float(window), "tenants": out}

    async def _route(self, method, path, headers, body, trace=None,
                     query=""):
        if method == "POST" and path == "/generate":
            reply = await self._maybe_disagg(body, headers, trace)
            if reply is not None:
                return reply
        if method == "POST" and path in self.FORWARD_POSTS:
            return await self._forward_request(path, body, headers,
                                               trace=trace)
        if method == "GET" and path == "/v1/models":
            return await self._forward_request(path, b"", headers,
                                               method="GET",
                                               trace=trace)
        if method == "GET" and path == "/debug/requests":
            # live in-flight table (loop thread owns _inflight — no
            # locks needed, same invariant as the replica registry)
            return (200, {"Content-Type": "application/json"},
                    json.dumps({"role": "router",
                                "requests": self._inflight_rows()},
                               default=str).encode())
        if method == "GET" and path == "/healthz":
            state = await self._state()
            ok = state["eligible"] > 0
            return (200 if ok else 503,
                    {"Content-Type": "application/json"},
                    json.dumps({
                        "status": "ok" if ok else "unavailable",
                        "role": "router",
                        "replicas": len(self._replicas),
                        "eligible": state["eligible"]}).encode())
        if method == "GET" and path == "/router/state":
            return (200, {"Content-Type": "application/json"},
                    json.dumps(await self._state(),
                               default=str).encode())
        if method == "GET" and path == "/metrics":
            from veles_tpu.telemetry import metrics as registry
            return (200, {"Content-Type":
                          "text/plain; version=0.0.4; charset=utf-8"},
                    registry.render_prometheus().encode())
        if method == "GET" and path == "/metrics/fleet":
            from veles_tpu.telemetry import federation
            return (200, {"Content-Type":
                          "text/plain; version=0.0.4; charset=utf-8"},
                    federation.render_families_text(
                        self._fleet_families()).encode())
        if method == "GET" and path == "/metrics/history":
            if self.tsdb is None:
                return self._error(503, "tsdb disabled")
            from veles_tpu.telemetry.tsdb import history_query
            return (200, {"Content-Type": "application/json"},
                    json.dumps(history_query(self.tsdb, query),
                               default=str).encode())
        if method == "GET" and path == "/tenants/usage":
            from urllib.parse import parse_qs
            params = {k: v[-1]
                      for k, v in parse_qs(query or "").items()}
            try:
                window = float(params.get("window", 60.0))
            except ValueError:
                return self._error(400, "bad window")
            return (200, {"Content-Type": "application/json"},
                    json.dumps(self._tenant_usage(window=window),
                               default=str).encode())
        if method == "GET" and path == "/alerts":
            snap = self.alerts.snapshot() if self.alerts is not None \
                else {"enabled": False}
            return (200, {"Content-Type": "application/json"},
                    json.dumps(snap, default=str).encode())
        if method == "GET" and path == "/dashboard":
            from veles_tpu.telemetry.dashboard import \
                render_dashboard_html
            from veles_tpu.telemetry.tsdb import BUNDLE_SERIES
            state = await self._state()
            history = None
            if self.tsdb is not None:
                history = {}
                for series in BUNDLE_SERIES:
                    pts = self.tsdb.points(series, window=300.0,
                                           tier=0)
                    if pts:
                        history[series] = pts
            page = render_dashboard_html(
                "veles fleet — %s:%d" % (self.host, self.port),
                replicas=state["replicas"],
                slo=state["router"].get("slo"),
                alerts=self.alerts.snapshot()
                if self.alerts is not None else None,
                inflight=self._inflight_rows(),
                note="%d replica(s), %d eligible" % (
                    len(self._replicas), state["eligible"]),
                history=history,
                tenants=self._tenant_usage()
                if self.tsdb is not None else None)
            return (200,
                    {"Content-Type": "text/html; charset=utf-8"},
                    page.encode())
        return self._error(404, "no route %s %s" % (method, path))

    async def _serve_conn(self, reader, writer):
        try:
            line = (await reader.readline()).decode("latin-1")
            parts = line.split()
            if len(parts) < 2:
                return
            method, target = parts[0].upper(), parts[1]
            headers = {}
            while True:
                hline = await reader.readline()
                if hline in (b"\r\n", b"\n", b""):
                    break
                key, _, val = hline.decode("latin-1").partition(":")
                headers[key.strip().lower()] = val.strip()
            length = int(headers.get("content-length", 0))
            body = await reader.readexactly(length) if length \
                else b""
            path, _, query = target.partition("?")
            path = path.rstrip("/") or "/"
            # the EDGE mint: accept the client's X-Veles-Trace when
            # sane, else mint — and propagate it to the replica via
            # the same (sanitized) header so one id spans the fleet
            trace = reqtrace.ensure_trace_id(
                headers.get("x-veles-trace"))
            headers["x-veles-trace"] = trace
            # tenant identity at the edge: EVERY request is resolved
            # and tagged (the forwarded x-veles-tenant header is the
            # bounded label — replica spans and metrics then agree
            # with the router's); the token bucket and the fair lane
            # judge only the forwarded data-plane POSTs
            peer = writer.get_extra_info("peername")
            raw_tenant = self.tenants.tag(
                headers, loopback=bool(peer)
                and peer[0] in ("127.0.0.1", "::1", "localhost"))
            tenant = headers["x-veles-tenant"]
            reply = None
            seat = None
            if method == "POST" and path in self.FORWARD_POSTS:
                after = self.tenants.throttle(raw_tenant)
                if after is not None:
                    reply = self._error(
                        429, "tenant %s over its rate limit"
                        % tenant, retry_after=after, tenant=tenant,
                        trace=trace)
                else:
                    # the weighted-fair lane: the wait happens in the
                    # TENANT'S own queue — other tenants' traffic
                    # never sits behind it
                    seat = await self.tenants.acquire(
                        raw_tenant, self.request_timeout)
                    if seat is None:
                        reply = self._error(
                            429, "tenant %s concurrency lane stayed "
                            "full" % tenant,
                            retry_after=self.shed_retry_after,
                            tenant=tenant, trace=trace)
            try:
                if reply is None and method == "POST" \
                        and path in self.FORWARD_POSTS \
                        and self._inspect(body, headers)[2]:
                    # SSE streaming: the proxy writes the whole
                    # client response itself (headers relay chunk by
                    # chunk; first forwarded byte pins the replica)
                    await self._stream_proxy(path, headers, body,
                                             writer, trace=trace)
                    return
                if reply is None:
                    try:
                        reply = await self._route(
                            method, path, headers, body, trace=trace,
                            query=query)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        # the router must outlive any bug
                        reply = self._error(
                            500, "router error: %r" % (e,),
                            trace=trace)
            finally:
                if seat == "seat":
                    self.tenants.release(raw_tenant)
            status, rheaders, rbody = reply
            rheaders.setdefault("X-Veles-Trace", trace)
            reason = {200: "OK", 202: "Accepted"}.get(status, "X")
            out = ["HTTP/1.1 %d %s" % (status, reason),
                   "Connection: close",
                   "Content-Length: %d" % len(rbody)]
            out += ["%s: %s" % (k, v) for k, v in rheaders.items()]
            writer.write(("\r\n".join(out) + "\r\n\r\n").encode()
                         + rbody)
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # client went away mid-request
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass
