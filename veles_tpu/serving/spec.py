"""Speculative decoding: the n-gram / prompt-lookup draft proposer.

Decode is one full model pass per token.  Speculative decoding drafts
``k`` candidate tokens CHEAPLY, then scores the pending token plus
all k drafts in ONE batched verify pass
(:func:`veles_tpu.serving.engine.verify_step_paged`) and keeps the
longest accepted prefix — so an iteration that accepts ``a`` drafts
emits ``a + 1`` tokens for one model pass instead of one.

The proposer here is the *self-speculative* n-gram / prompt-lookup
family (Saxena's prompt-lookup decoding; the ``[ngram]`` draft model
of vLLM): the draft for the next tokens is whatever FOLLOWED the most
recent previous occurrence of the context's trailing n-gram.  No
second model, no extra weights, no quality risk — acceptance keeps
the output distribution exactly the target model's (greedy and
per-seed sampling; see the acceptance rule in ``verify_step_paged``),
and a draft that never matches merely degrades to plain decoding.
It shines on repetitive text: code, templated prose, long copies of
the prompt — exactly the traffic a serving fleet sees most.

Host-side and stateless per call: the scheduler owns one proposer and
calls :meth:`NgramProposer.propose` per active slot per iteration.
"""


class NgramProposer:
    """Draft up to ``k`` tokens by prompt lookup: find the most
    recent earlier occurrence of the context's trailing ``n``-gram
    (longest n first, ``max_ngram`` down to ``min_ngram``) and
    propose the tokens that followed it.

    ``propose`` is O(len(context) · max_ngram) per call on the host —
    noise next to a model pass, and only ever invoked for slots that
    are actively decoding."""

    def __init__(self, k=4, max_ngram=3, min_ngram=1):
        self.k = int(k)
        self.max_ngram = int(max_ngram)
        self.min_ngram = max(1, int(min_ngram))
        if self.k < 1:
            raise ValueError("need k >= 1")
        if self.max_ngram < self.min_ngram:
            raise ValueError("max_ngram < min_ngram")

    def propose(self, context, max_tokens=None):
        """Draft tokens continuing ``context`` (a list of ints, the
        request's prompt + generated stream).  Returns a list of at
        most ``min(k, max_tokens)`` drafted ids — empty when no
        earlier occurrence of the trailing n-gram exists (the caller
        then runs a plain decode step for that slot)."""
        limit = self.k if max_tokens is None \
            else min(self.k, int(max_tokens))
        n_ctx = len(context)
        if limit < 1 or n_ctx < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_ctx - 1),
                       self.min_ngram - 1, -1):
            tail = context[n_ctx - n:]
            # scan right-to-left for the most recent PRIOR occurrence
            # (recent text predicts the continuation best)
            for j in range(n_ctx - n - 1, -1, -1):
                if context[j:j + n] == tail:
                    cont = context[j + n:j + n + limit]
                    if cont:
                        return list(cont)
        return []


def accept_drafts(drafts, sampled):
    """The host half of the verify contract: given the ``drafts``
    [d_1..d_m] a slot proposed and the ``sampled`` [s_0..s_m] tokens
    the verify pass emitted (s_j = the token sequential decode would
    produce after the context extended by d_1..d_j), return the
    accepted token run.

    s_0 is always valid (it needed no drafts).  s_j is valid iff
    every earlier draft matched its sample (d_i == s_{i-1}); the
    first mismatching position still CONTRIBUTES its sample — the
    model already told us the right token there (the "free"
    correction) — and everything after it is rolled back.  Greedy or
    per-seed sampled, the emitted run is bit-identical to the tokens
    a sequential spec-off decode would have produced."""
    out = [int(sampled[0])]
    for j in range(1, len(drafts) + 1):
        if int(drafts[j - 1]) != out[-1]:
            break
        out.append(int(sampled[j]))
    return out
