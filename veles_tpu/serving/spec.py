"""Speculative decoding: the n-gram / prompt-lookup draft proposer.

Decode is one full model pass per token.  Speculative decoding drafts
``k`` candidate tokens CHEAPLY, then scores the pending token plus
all k drafts in ONE batched verify pass
(:func:`veles_tpu.serving.engine.verify_step_paged`) and keeps the
longest accepted prefix — so an iteration that accepts ``a`` drafts
emits ``a + 1`` tokens for one model pass instead of one.

The proposer here is the *self-speculative* n-gram / prompt-lookup
family (Saxena's prompt-lookup decoding; the ``[ngram]`` draft model
of vLLM): the draft for the next tokens is whatever FOLLOWED the most
recent previous occurrence of the context's trailing n-gram.  No
second model, no extra weights, no quality risk — acceptance keeps
the output distribution exactly the target model's (greedy and
per-seed sampling; see the acceptance rule in ``verify_step_paged``),
and a draft that never matches merely degrades to plain decoding.
It shines on repetitive text: code, templated prose, long copies of
the prompt — exactly the traffic a serving fleet sees most.

Host-side and stateless per call: the scheduler owns one proposer and
calls :meth:`NgramProposer.propose` per active slot per iteration.
Long contexts can hand ``propose`` a per-request :class:`NgramIndex`
— an incrementally-maintained map from every n-gram to its two most
recent occurrence starts — turning the O(len·max_ngram) right-to-left
rescan into an O(max_ngram) lookup after an O(max_ngram)-per-new-token
sync (the context is append-only, so the index never rebuilds).
"""


class NgramIndex:
    """Incremental trailing-n-gram index over ONE request's
    append-only context: ``_last[gram] = (last_start, prev_start)``
    — the start offsets of the gram's most recent and second most
    recent occurrences (``None`` when it has appeared only once).
    After :meth:`sync`, the trailing gram's most recent occurrence is
    the tail itself, so ``prev_start`` IS the "most recent PRIOR
    occurrence" the scan-based proposer finds — same answer, O(1)
    per gram length instead of a rescan of the whole context."""

    def __init__(self, max_ngram=3, min_ngram=1):
        self.max_ngram = int(max_ngram)
        self.min_ngram = max(1, int(min_ngram))
        self.n = 0          # context prefix already indexed
        self._last = {}

    def sync(self, context):
        """Fold any newly APPENDED tokens into the index.  A context
        shorter than what was already indexed means the caller's
        stream was rewritten (never happens in the scheduler — a
        preempt-resume re-prefills the same tokens) — rebuild from
        scratch rather than serve stale offsets."""
        if len(context) < self.n:
            self.n = 0
            self._last.clear()
        for i in range(self.n, len(context)):
            for g in range(self.min_ngram,
                           min(self.max_ngram, i + 1) + 1):
                s = i - g + 1
                gram = tuple(context[s:i + 1])
                prev = self._last.get(gram)
                self._last[gram] = (
                    s, prev[0] if prev is not None else None)
        self.n = len(context)

    def prior(self, gram):
        """Start offset of the most recent occurrence of ``gram``
        BEFORE its trailing occurrence, or None."""
        entry = self._last.get(tuple(gram))
        return entry[1] if entry is not None else None


class NgramProposer:
    """Draft up to ``k`` tokens by prompt lookup: find the most
    recent earlier occurrence of the context's trailing ``n``-gram
    (longest n first, ``max_ngram`` down to ``min_ngram``) and
    propose the tokens that followed it.

    ``propose`` is O(len(context) · max_ngram) per call on the host —
    noise next to a model pass, and only ever invoked for slots that
    are actively decoding."""

    def __init__(self, k=4, max_ngram=3, min_ngram=1):
        self.k = int(k)
        self.max_ngram = int(max_ngram)
        self.min_ngram = max(1, int(min_ngram))
        if self.k < 1:
            raise ValueError("need k >= 1")
        if self.max_ngram < self.min_ngram:
            raise ValueError("max_ngram < min_ngram")

    def propose(self, context, max_tokens=None, index=None):
        """Draft tokens continuing ``context`` (a list of ints, the
        request's prompt + generated stream).  Returns a list of at
        most ``min(k, max_tokens)`` drafted ids — empty when no
        earlier occurrence of the trailing n-gram exists (the caller
        then runs a plain decode step for that slot).

        ``index`` (an optional per-request :class:`NgramIndex`) makes
        the lookup O(max_ngram) instead of a right-to-left context
        rescan — same drafts, memoized (the index syncs itself to any
        tokens appended since its last call)."""
        limit = self.k if max_tokens is None \
            else min(self.k, int(max_tokens))
        n_ctx = len(context)
        if limit < 1 or n_ctx < self.min_ngram + 1:
            return []
        if index is not None:
            index.sync(context)
        for n in range(min(self.max_ngram, n_ctx - 1),
                       self.min_ngram - 1, -1):
            tail = context[n_ctx - n:]
            if index is not None:
                j = index.prior(tail)
                if j is not None:
                    cont = context[j + n:j + n + limit]
                    if cont:
                        return list(cont)
                continue
            # scan right-to-left for the most recent PRIOR occurrence
            # (recent text predicts the continuation best)
            for j in range(n_ctx - n - 1, -1, -1):
                if context[j:j + n] == tail:
                    cont = context[j + n:j + n + limit]
                    if cont:
                        return list(cont)
        return []


def accept_drafts(drafts, sampled):
    """The host half of the verify contract: given the ``drafts``
    [d_1..d_m] a slot proposed and the ``sampled`` [s_0..s_m] tokens
    the verify pass emitted (s_j = the token sequential decode would
    produce after the context extended by d_1..d_j), return the
    accepted token run.

    s_0 is always valid (it needed no drafts).  s_j is valid iff
    every earlier draft matched its sample (d_i == s_{i-1}); the
    first mismatching position still CONTRIBUTES its sample — the
    model already told us the right token there (the "free"
    correction) — and everything after it is rolled back.  Greedy or
    per-seed sampled, the emitted run is bit-identical to the tokens
    a sequential spec-off decode would have produced."""
    out = [int(sampled[0])]
    for j in range(1, len(drafts) + 1):
        if int(drafts[j - 1]) != out[-1]:
            break
        out.append(int(sampled[j]))
    return out
