"""Batched prompt prefill — one jitted pass over the whole prompt.

The pre-serving decode stack consumed prompts one token at a time
(``_make_pre_step`` scanning ``apply_step`` — O(prompt_len) compiled
steps before the first generated token).  :func:`prefill` runs the
chain ONCE over all prompt positions, writes every cacheable block's
K/V rows in that single pass, and returns the logits at each row's
last prompt position — everything a request needs to emit its first
token and start single-token decoding.

Ragged batches prefill together: ``prompt_lens`` rides the compiled
pass as a traced argument (one executable serves any length mix at the
same shapes), rows at or past a row's length are zeroed in the cache
(exactly the rows a per-row sequential prefill would have left at the
``init_cache`` zeros), and the last-position logits gather follows the
per-row lengths.
"""

import functools

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.models.generate import (
    _StepClosure, _arch_sig, _check_positions, _device_params,
    kv_cache_eligible)
from veles_tpu.telemetry import track_jit


def serving_supported(forwards):
    """True when the chain can serve through the slot scheduler:
    kv-cache eligible AND every cacheable block speaks the serving
    step shapes (``apply_prefill`` + ``apply_step_slots``) AND every
    other sequence-dependent unit has a per-slot step or is
    position-wise."""
    if not kv_cache_eligible(forwards):
        return False
    has_cache = False
    for u in forwards:
        if hasattr(u, "init_cache"):
            has_cache = True
            if not hasattr(u, "apply_prefill") \
                    or not hasattr(u, "apply_step_slots"):
                return False
        elif hasattr(u, "apply_step") \
                and not getattr(u, "DECODE_POINTWISE", False) \
                and not hasattr(u, "apply_step_slots"):
            return False
    return has_cache


def serving_window(forwards):
    """The widest decode window the chain supports, from the smallest
    learned positional table in the chain — None when no unit bounds
    the sequence length (the scheduler then requires an explicit
    window)."""
    best = None
    for u in forwards:
        pos_table = getattr(u, "positions", None)
        if pos_table is not None and hasattr(pos_table, "shape") \
                and len(pos_table.shape) == 2:
            n = int(pos_table.shape[0])
            best = n if best is None else min(best, n)
    return best


def chunked_supported(forwards):
    """True when the chain can prefill in chunks: every cacheable
    block continues from an offset (``apply_prefill_chunk``) and every
    other sequence-positioned unit speaks chunk offsets
    (``apply_chunk``) or is position-wise."""
    has = False
    for u in forwards:
        if hasattr(u, "init_cache"):
            has = True
            if not hasattr(u, "apply_prefill_chunk"):
                return False
        elif getattr(u, "positions", None) is not None \
                and not hasattr(u, "apply_chunk"):
            return False
    return has


def _make_chunk_fn(forwards, key_width):
    cacheable = frozenset(i for i, u in enumerate(forwards)
                          if hasattr(u, "init_cache"))

    def run(params, chunk, offset, chunk_lens, caches):
        h = chunk
        out = dict(caches)
        for i, u in enumerate(forwards):
            if i in cacheable:
                h, out[i] = u.apply_prefill_chunk(
                    params[i], h, caches[i], offset,
                    chunk_lens=chunk_lens, key_width=key_width)
            elif hasattr(u, "apply_chunk"):
                h = u.apply_chunk(params[i], h, offset)
            else:
                h = u.apply(params[i], h)
        last = jnp.take_along_axis(
            h, (chunk_lens - 1)[:, None, None], axis=1)[:, 0]
        return out, last.astype(jnp.float32)
    return run


@functools.lru_cache(maxsize=64)
def _chunk_cached(cache_key, closure):
    return track_jit("serving.prefill_chunk", jax.jit(closure.fn))


def clear_chunk_cache():
    """Drop the compiled chunk-prefill cache (same lifetime note as
    :func:`clear_prefill_cache`)."""
    _chunk_cached.cache_clear()


def prefill_chunk(forwards, chunk, offset, chunk_lens, caches,
                  key_width=None, tp=None):
    """Prefill ONE chunk — ``chunk`` [batch, C] int32 tokens at
    sequence positions [offset, offset+C) — into existing staging
    ``caches`` (``{chain index: {"k", "v"} [batch, W, d]}``; W a
    multiple of C, rows still zero past every previously-written
    position).

    ``offset`` (a host int, multiple of C) rides the executable as a
    traced scalar; ``chunk_lens`` [batch] ints mark how much of the
    chunk each row's prompt actually covers (pad the rest — its K/V
    rows are zeroed like one-shot prefill's ragged rows).
    ``key_width`` (static, default W) bounds the attended key range;
    callers bucket it to a power of two ≥ offset + C.

    Returns ``(caches', last_logits)`` where ``last_logits``
    [batch, vocab] (f32) sit at each row's position
    ``offset + chunk_lens[n] - 1`` — the first-token logits once the
    final chunk lands.  Running the chunks in order reproduces the
    one-shot :func:`prefill` cache rows and logits (tested).

    ``tp`` (a :class:`serving.tp.ServingTP`, default None) runs the
    chunk SPMD over the tensor-parallel mesh with Megatron-sharded
    params — the staging caches ride uncommitted and land wherever
    GSPMD places them; the later block insert re-places them against
    the head-sharded pools."""
    from veles_tpu import dtypes
    if not chunked_supported(forwards):
        raise ValueError("chain cannot prefill in chunks (see "
                         "chunked_supported)")
    params = tp.device_params(forwards) if tp is not None \
        else _device_params(forwards)
    chunk = jnp.asarray(chunk, jnp.int32)
    b, c = chunk.shape
    widths = {tuple(a.shape[1] for a in layer.values())
              for layer in caches.values()}
    w = next(iter(widths))[0]
    if any(x != w for tup in widths for x in tup):
        raise ValueError("staging caches disagree on width")
    if w % c or offset % c or offset + c > w:
        raise ValueError(
            "chunk [%d, %d) must tile the staging width %d"
            % (offset, offset + c, w))
    kw = int(key_width or w)
    if kw > w or kw < min(offset + c, w):
        raise ValueError("key_width %d outside [%d, %d]"
                         % (kw, offset + c, w))
    lens_np = numpy.asarray(chunk_lens, numpy.int32)
    if lens_np.shape != (b,):
        raise ValueError("chunk_lens must be [batch] ints")
    if lens_np.min() < 1 or lens_np.max() > c:
        raise ValueError("chunk_lens must be in [1, %d]" % c)
    cache_key = (_arch_sig(forwards), b, c, w, kw,
                 tp.size if tp is not None else 1,
                 str(dtypes.compute_dtype()),
                 str(dtypes.matmul_precision()))
    fn = _chunk_cached(cache_key,
                       _StepClosure(_make_chunk_fn(forwards, kw)))
    return fn(params, chunk, jnp.int32(offset),
              jnp.asarray(lens_np), caches)


def _make_prefill_fn(forwards, window):
    cacheable = frozenset(i for i, u in enumerate(forwards)
                          if hasattr(u, "init_cache"))

    def run(params, prompt, lens):
        from veles_tpu import dtypes
        b = prompt.shape[0]
        caches = {i: forwards[i].init_cache(b, window,
                                            dtypes.compute_dtype())
                  for i in cacheable}
        h = prompt
        for i, u in enumerate(forwards):
            if i in cacheable:
                h, caches[i] = u.apply_prefill(params[i], h,
                                               caches[i], lens=lens)
            else:
                h = u.apply(params[i], h)
        # h: [b, P, vocab]; each row's next token is predicted by the
        # logits at ITS last prompt position
        last = jnp.take_along_axis(
            h, (lens - 1)[:, None, None], axis=1)[:, 0]
        return caches, last.astype(jnp.float32)
    return run


@functools.lru_cache(maxsize=32)
def _prefill_cached(cache_key, closure):
    return track_jit("serving.prefill", jax.jit(closure.fn))


def clear_prefill_cache():
    """Drop the compiled-prefill cache (same lifetime note as
    ``generate.clear_decode_caches``: entries pin the chain's units)."""
    _prefill_cached.cache_clear()


def prefill(forwards, prompt, prompt_lens=None, window=None,
            tp=None):
    """Prefill ``prompt`` [batch, P] (int32, front-aligned rows) in
    ONE compiled pass.

    Returns ``(caches, last_logits)``: ``caches`` maps the chain index
    of every cacheable block to its ``{"k", "v"}`` buffers —
    [batch, window, d] with rows [0, lens[n]) holding the prompt's K/V
    and every later row zero; ``last_logits`` [batch, vocab] (f32) are
    the logits at each row's position ``lens[n] - 1``.

    ``prompt_lens`` (optional [batch] ints) marks ragged rows (pad the
    array arbitrarily past each length); it rides the executable as a
    traced argument.  ``window`` (default P) sizes the returned cache
    buffers — a request decoding into a slot cache prefills straight
    at the slot width.  ``tp`` (serving/tp.py context) runs the pass
    SPMD over the tensor-parallel mesh."""
    from veles_tpu import dtypes
    for u in forwards:
        if hasattr(u, "init_cache") \
                and not hasattr(u, "apply_prefill"):
            raise ValueError(
                "batched prefill: %s has no apply_prefill"
                % type(u).__name__)
    params = tp.device_params(forwards) if tp is not None \
        else _device_params(forwards)
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    window = int(window or p)
    if window < p:
        raise ValueError("window %d < prompt width %d" % (window, p))
    _check_positions(forwards, p)
    if prompt_lens is None:
        lens = jnp.full((b,), p, jnp.int32)
    else:
        lens_np = numpy.asarray(prompt_lens, numpy.int32)
        if lens_np.shape != (b,):
            raise ValueError("prompt_lens must be [batch] ints")
        if lens_np.min() < 1 or lens_np.max() > p:
            raise ValueError(
                "prompt_lens must be in [1, %d] (the prompt width)"
                % p)
        lens = jnp.asarray(lens_np)
    cache_key = (_arch_sig(forwards), b, p, window,
                 tp.size if tp is not None else 1,
                 str(dtypes.compute_dtype()),
                 str(dtypes.matmul_precision()))
    fn = _prefill_cached(cache_key,
                         _StepClosure(_make_prefill_fn(forwards,
                                                       window)))
    return fn(params, prompt, lens)
