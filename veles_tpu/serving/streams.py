"""Per-request incremental token delivery — the subscription half of
the streaming serving surface.

The scheduler already produces tokens one decode boundary at a time
(and in BURSTS when speculative decoding accepts drafts); before this
module they pooled in ``_Request.generated`` until the future resolved
— a 200-token reply reached the client only after token 200.  A
:class:`TokenStream` is a thread-safe subscription over ONE in-flight
request: ``InferenceScheduler.submit(..., stream=True)`` returns one,
and the decode loop pushes every ACCEPTED token into it at the same
boundary it appends to ``generated`` — iteration yields tokens with
per-token latency, spec-decode bursts arrive back to back, and a
preempt→resume emits nothing twice (only newly drawn tokens are
pushed, and the resumed stream is bit-identical anyway).

Termination rides the request future: its done-callback enqueues a
sentinel AFTER the loop thread pushed the final token (same producer
thread, FIFO queue — no token can trail the sentinel), so iteration
ends exactly at completion, or re-raises the scheduler error
(deadline, cancel, watchdog, close) after yielding everything the
client's budget actually bought.

:func:`sse_event` / :data:`SSE_DONE` are the wire helpers the REST
layer and the OpenAI facade share: one Server-Sent-Events frame per
JSON payload, ``data: [DONE]`` as the terminal frame (the OpenAI
convention, harmless on the native endpoint).

This module deliberately imports nothing from the scheduler — the
scheduler imports *it* (streams are a delivery concern, not a
scheduling one).
"""

import json
import queue

#: queue sentinel marking the end of a stream (the request future is
#: resolved by the time a consumer sees it)
_DONE = object()

#: the terminal SSE frame every streaming endpoint sends (OpenAI
#: convention; clients stop reading at it)
SSE_DONE = b"data: [DONE]\n\n"


def sse_event(payload):
    """One Server-Sent-Events frame: ``data: <json>\\n\\n`` bytes.
    Payloads are compact JSON (no spaces) — SSE frames are a wire
    format, not a display one."""
    return b"data: " + json.dumps(
        payload, separators=(",", ":")).encode() + b"\n\n"


class StreamTimeoutError(Exception):
    """No token arrived within the consumer's per-token timeout (the
    request itself keeps decoding — the consumer chose to stop
    waiting)."""


class TokenStream(object):
    """Iterable subscription over one request's accepted tokens.

    Produced by ``InferenceScheduler.submit(..., stream=True)``.
    Iterating yields generated token ids (ints) as the decode loop
    accepts them and ends when the request completes; a failed
    request re-raises its scheduler error from the iterator after
    every already-accepted token was yielded.  ``tokens`` accumulates
    what iteration delivered so far, ``result(timeout)`` blocks for
    the request's full prompt+generated list (the batch-path reply),
    and ``cancel()`` releases the request's slot and KV blocks at the
    next decode boundary — the mid-stream-disconnect hook.
    """

    def __init__(self, prompt, token_timeout=None):
        self.prompt = [int(t) for t in prompt]
        #: generated tokens yielded so far (iteration order)
        self.tokens = []
        #: per-token consumer patience in seconds (None blocks —
        #: safe: every future resolves via deadline/watchdog/close)
        self.token_timeout = token_timeout
        #: the request's trace id (set at submit) — what the SSE
        #: terminal frame echoes so a streamed reply is correlatable
        #: with the server-side phase timeline
        self.trace = None
        self.future = None
        self._scheduler = None
        self._q = queue.SimpleQueue()

    # -- producer side (scheduler) --------------------------------------

    def _bind(self, scheduler, future):
        """Called once at submit: wire the request future in.  The
        done-callback runs on whichever thread resolves the future —
        for tokens that is the decode loop AFTER its final push, so
        FIFO order guarantees the sentinel trails every token."""
        self._scheduler = scheduler
        self.future = future
        future.add_done_callback(lambda _f: self._q.put(_DONE))

    def _push(self, token):
        """Decode-loop hook: one accepted token (spec bursts call
        this back to back)."""
        self._q.put(int(token))

    # -- consumer side ---------------------------------------------------

    def __iter__(self):
        while True:
            try:
                item = self._q.get(timeout=self.token_timeout)
            except queue.Empty:
                raise StreamTimeoutError(
                    "no token within %.1fs" % self.token_timeout)
            if item is _DONE:
                err = self.future.exception()
                if err is not None:
                    raise err
                return
            self.tokens.append(item)
            yield item

    def result(self, timeout=None):
        """The complete prompt + generated token list — exactly the
        non-streaming submit's future result."""
        return self.future.result(timeout)

    def cancel(self, reason="stream consumer disconnected"):
        """Cancel the underlying request (client went away): queued
        requests fail immediately, in-flight ones free their slot and
        KV blocks at the next decode boundary."""
        return self._scheduler.cancel(self.future, reason=reason)

    @property
    def done(self):
        return self.future is not None and self.future.done()
