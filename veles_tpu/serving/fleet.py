"""Replica supervision for the router fleet: spawn N engine replicas,
monitor them, respawn the dead, and orchestrate zero-downtime rolling
restarts through :class:`veles_tpu.serving.router.Router`.

The Veles DCN contract (the master re-distributes a dead worker's
work) applied to serving: a replica process is EXPECTED to die, and
the fleet's job is to make that invisible — the router retries the
victim's in-flight requests elsewhere while the :class:`Fleet`
supervisor respawns it and re-registers it for traffic.

A *replica handle* is anything with ``host``/``port``/``alive()``/
``stop()`` (and optionally ``replica_id``): :class:`LocalReplica`
wraps an in-process :class:`~veles_tpu.restful_api.RESTfulAPI` (the
tier-1 and bench shape — every replica still gets its OWN scheduler
thread and KV cache), :class:`SubprocessReplica` runs a serving
process from an argv template (the deployment shape).  ``Fleet``
only sees the protocol, so chaos tests kill in-process replicas the
same way production loses containers.

Spawn attempts pass through the ``fleet.replica.spawn`` fault point
(keyed by replica index) — an armed ``exception`` makes respawn fail
and exercises the capped-backoff retry; ``hang`` delays recovery.

**Role rebalancing** (disaggregated fleets, policy knob
``root.common.fleet.rebalance``, default on): a fleet of
specialists must never lose a whole ROLE pool to one death.  Two
mechanisms cooperate, both counted in
``veles_fleet_rebalances_total{role}``:

- every (re)spawn decides its role through :meth:`Fleet._assign_role`
  — the index's own pool membership by default, but when another
  desired role's pool has ZERO live members (and the index's own
  pool keeps one), the respawn fills the empty pool instead (fault
  point ``fleet.role.assign``, keyed by index; ``drop`` pins the
  original role);
- the monitor runs :meth:`Fleet.rebalance` each tick: when a
  desired pool stays empty and no respawn is filling it (the dead
  index's spawns keep failing), the youngest replica of a pool with
  >= 2 live members is restarted INTO the empty role (fault point
  ``fleet.role.rebalance``; ``drop`` skips the pass).  Rebalancing
  restores role COVERAGE, not proportions — a 2:1 fleet that ends
  1:2 after an episode is alive, which is the contract.

Rolling restart (:meth:`Fleet.rolling_restart`), one replica at a
time, zero failed client requests end to end:

1. ``router.drain_replica(id)`` — routing stops immediately (the
   "draining" state, NOT a breaker trip), then ``POST /drain`` closes
   the replica's admission while in-flight requests finish;
2. poll the replica's ``/healthz`` until ``drained`` (in-flight 0);
3. stop the old handle, spawn a fresh one (same index, next
   generation);
4. re-register with the router — the registration probe re-admits it
   as soon as ``/healthz`` answers 200.
"""

import json
import subprocess
import threading
import time
import urllib.error
import urllib.request

from veles_tpu import faults
from veles_tpu.logger import Logger


def _get_json(host, port, path, timeout=5.0):
    """GET a replica endpoint, returning (status, body-dict) — error
    statuses still parse their structured JSON body (a draining
    /healthz answers 503 WITH the drain progress)."""
    url = "http://%s:%d%s" % (host, port, path)
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode())
        except Exception:
            return e.code, {}


class LocalReplica(object):
    """In-process replica handle around a started
    :class:`~veles_tpu.restful_api.RESTfulAPI` (plus its loader, when
    the caller wants it closed on stop)."""

    def __init__(self, api, loader=None):
        self.api = api
        self.loader = loader
        self.host = api.host
        self.port = api.port
        self.replica_id = api.replica_id

    def alive(self):
        return self.api._server_ is not None

    def stop(self):
        """Stop serving.  On a drained replica this is graceful; on a
        busy one it is the crash shape — pending futures fail and
        in-flight handlers answer 5xx, which is exactly what the
        router's retries exist to absorb."""
        self.api.stop()
        if self.loader is not None:
            self.loader.close()


class SubprocessReplica(object):
    """Replica handle over a serving subprocess: ``argv`` is launched
    as-is (the caller bakes host/port in; ``free_port()`` helps), and
    liveness is the process's own."""

    def __init__(self, argv, host, port, env=None):
        self.host = host
        self.port = int(port)
        self.replica_id = None   # defer to the replica's own pid:port
        self.proc = subprocess.Popen(argv, env=env)

    def alive(self):
        return self.proc.poll() is None

    def stop(self):
        self.proc.terminate()
        try:
            self.proc.wait(10)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(10)


def _rebalance_metric():
    from veles_tpu.telemetry import metrics
    return metrics.counter(
        "veles_fleet_rebalances_total",
        "replica role re-assignments (a respawn filling an empty "
        "role pool, or the monitor restarting a surplus replica "
        "into one), by the role assigned TO",
        labelnames=("role",))


def free_port(host="127.0.0.1"):
    """Ask the OS for an ephemeral port (subprocess replicas need the
    port chosen BEFORE exec)."""
    import socket
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class Fleet(Logger):
    """Spawn/supervise ``n`` replicas and keep them registered with
    ``router``.  ``spawn(index)`` returns a replica handle; the
    monitor thread respawns any handle whose ``alive()`` goes False
    (capped-backoff retries through the ``fleet.replica.spawn`` fault
    point)."""

    def __init__(self, spawn, n, router=None, monitor_interval=0.25,
                 spawn_retries=5, spawn_delay=0.2, spawn_cap=5.0,
                 roles=None, rebalance=None):
        super(Fleet, self).__init__()
        self.spawn = spawn
        self.n = int(n)
        #: disaggregated fleets: per-index serving role — ``roles``
        #: is a sequence cycled over the replica indices (e.g.
        #: ("prefill", "decode", "decode")); when set, ``spawn`` is
        #: called as ``spawn(index, role)`` so a respawned replica
        #: keeps its pool membership across generations.  None keeps
        #: the legacy ``spawn(index)`` homogeneous-fleet contract.
        self.roles = tuple(roles) if roles else None
        if self.roles:
            bad = [r for r in self.roles
                   if r not in ("prefill", "decode", "both")]
            if bad:
                raise ValueError(
                    "roles must be prefill/decode/both, got %s"
                    % bad)
        if rebalance is None:
            from veles_tpu.config import root
            rebalance = root.common.fleet.get("rebalance", True)
        #: role-rebalancing policy (module docstring): off, a dead
        #: pool stays dead until a human re-roles the fleet
        self.rebalance_enabled = bool(rebalance) and bool(self.roles)
        self.router = router
        self.monitor_interval = float(monitor_interval)
        self.spawn_retries = int(spawn_retries)
        self.spawn_delay = float(spawn_delay)
        self.spawn_cap = float(spawn_cap)
        self._replicas = {}     # index -> handle (None: spawn owed)
        self._ids = {}          # index -> router replica id
        self._generation = {}   # index -> spawn count
        self._role_of = {}      # index -> CURRENT role (rebalanced)
        self._busy = set()      # indices mid-rolling-restart
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._thread = None

    # -- lifecycle -------------------------------------------------------

    def start(self):
        for i in range(self.n):
            self._spawn_one(i)
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._monitor, daemon=True,
                    name="fleet-monitor")
                self._thread.start()
        return self

    def stop(self):
        self._stopping.set()
        with self._lock:
            thread, self._thread = self._thread, None
            handles = dict(self._replicas)
            ids = dict(self._ids)
            self._replicas = {}
            self._ids = {}
        if thread is not None:
            thread.join(10)
        for i, handle in handles.items():
            if self.router is not None and i in ids:
                try:
                    self.router.remove_replica(ids[i])
                except Exception:
                    pass
            if handle is not None:
                handle.stop()

    def handles(self):
        """Live handles snapshot (index -> handle), e.g. for per-
        replica KV-leak checks after a soak."""
        with self._lock:
            return dict(self._replicas)

    def replica_id(self, index):
        with self._lock:
            return self._ids.get(index)

    def role_of(self, index):
        """The role replica ``index`` currently serves (None for a
        homogeneous fleet) — tracks rebalancing re-assignments."""
        if not self.roles:
            return None
        with self._lock:
            return self._role_of.get(
                index, self.roles[index % len(self.roles)])

    def index_of(self, replica_id):
        """The fleet index currently serving router id
        ``replica_id`` (None when unknown) — how the control plane
        maps a router replica view back onto a fleet slot."""
        with self._lock:
            for index, rid in self._ids.items():
                if rid == replica_id:
                    return index
        return None

    # -- spawning --------------------------------------------------------

    def _live_role_counts(self, exclude=None):
        """Live members per role (``_role_of`` over alive handles),
        skipping ``exclude`` — the pool-health view both rebalance
        mechanisms decide from.  Takes the lock."""
        with self._lock:
            live = [self._role_of.get(
                        i, self.roles[i % len(self.roles)])
                    for i, h in self._replicas.items()
                    if i != exclude and h is not None and h.alive()]
        counts = {}
        for r in live:
            counts[r] = counts.get(r, 0) + 1
        return counts

    def _assign_role(self, index):
        """The role replica ``index`` (re)spawns with: its own pool
        by default; an EMPTY desired pool instead, when this index's
        own pool keeps a live member without it (the passive half of
        rebalancing — a respawn is a free chance to fix coverage)."""
        base = self._role_of.get(
            index, self.roles[index % len(self.roles)])
        if not self.rebalance_enabled:
            return base
        with self._lock:
            if self._generation.get(index, 0) == 0:
                # FIRST spawn: later indices have not spawned yet,
                # so every pool but the earliest looks empty — only
                # a RE-spawn may fill a pool emptied by death
                return base
        if faults.fire("fleet.role.assign", key=str(index)):
            return base      # armed drop pins the original role
        counts = self._live_role_counts(exclude=index)
        if counts.get(base, 0) == 0:
            return base      # respawning as base fills its own hole
        empty = sorted(r for r in set(self.roles)
                       if counts.get(r, 0) == 0)
        if not empty:
            return base
        role = empty[0]
        _rebalance_metric().labels(role=role).inc()
        self.warning("rebalance: replica %d re-roles %s -> %s (the "
                     "%s pool had no live member)", index, base,
                     role, role)
        return role

    def rebalance(self):
        """One ACTIVE rebalance pass (monitor-driven; also callable
        by an operator): when a desired role pool has zero live
        members and no dead index is about to fill it, restart the
        highest-index replica of a pool holding >= 2 live members
        into the empty role.  Returns the re-roled index, or None
        when coverage is already complete (or the pass was dropped
        at the ``fleet.role.rebalance`` point)."""
        if not self.rebalance_enabled:
            return None
        if faults.fire("fleet.role.rebalance"):
            return None
        counts = self._live_role_counts()
        empty = sorted(r for r in set(self.roles)
                       if counts.get(r, 0) == 0)
        if not empty:
            return None
        with self._lock:
            surplus = [
                i for i, h in self._replicas.items()
                if h is not None and h.alive()
                and i not in self._busy
                and counts.get(self._role_of.get(
                    i, self.roles[i % len(self.roles)]), 0) >= 2]
            if not surplus:
                return None
            victim = max(surplus)
            self._busy.add(victim)
        role = empty[0]
        try:
            with self._lock:
                old = self._ids.get(victim)
                handle = self._replicas.get(victim)
            self.warning("rebalance: restarting replica %d (%s) as "
                         "%s — the %s pool lost its last member",
                         victim, old, role, role)
            if self.router is not None and old is not None:
                try:
                    self.router.remove_replica(old)
                except Exception:
                    pass
            if handle is not None:
                handle.stop()
            with self._lock:
                self._role_of[victim] = role
            _rebalance_metric().labels(role=role).inc()
            self._spawn_one(victim)
        finally:
            with self._lock:
                self._busy.discard(victim)
        return victim

    # -- control-plane actuation (FleetController's verbs) ---------------

    def grow(self, role=None):
        """Scale-up: spawn one NEW replica at the next free index
        (optionally into ``role`` on a specialist fleet) and register
        it for traffic.  Returns the new index.  ``n`` is a
        high-water index bound, not a live count — indices are
        identities (generations, roles) and are never reused by a
        grow after a retire."""
        with self._lock:
            if self._stopping.is_set():
                raise RuntimeError("fleet is stopping")
            if role is not None:
                if not self.roles:
                    raise ValueError(
                        "role=%r on a homogeneous fleet" % role)
                if role not in ("prefill", "decode", "both"):
                    raise ValueError(
                        "roles must be prefill/decode/both, got %r"
                        % role)
            index = max(list(self._replicas) + [self.n - 1]) + 1
            self.n = index + 1
            if role is not None:
                self._role_of[index] = role
        self._spawn_one(index)
        return index

    def retire(self, index):
        """Scale-down removal of replica ``index``: forget it FIRST
        (so the monitor never respawns it), deregister from the
        router, stop the handle.  The caller drains beforehand — the
        controller's drain → poll-/healthz → retire path; retiring a
        busy replica is the crash shape the router's retries absorb.
        Returns the retired router id (None when the index was
        unknown)."""
        with self._lock:
            if index in self._busy:
                raise RuntimeError(
                    "replica %d is mid-restart" % index)
            handle = self._replicas.pop(index, None)
            rid = self._ids.pop(index, None)
            self._role_of.pop(index, None)
            self._generation.pop(index, None)
        if self.router is not None and rid is not None:
            try:
                self.router.remove_replica(rid)
            except Exception:
                pass
        if handle is not None:
            handle.stop()
        self.info("replica %d (%s) retired", index, rid)
        return rid

    def restart_as(self, index, role):
        """Load-driven re-roling (the controller's ratio loop):
        restart live replica ``index`` into ``role`` through the
        same spawn machinery a coverage rebalance uses.
        :meth:`rebalance` only ever FILLS an empty pool; this moves
        the prefill:decode RATIO on purpose.  Coverage still wins:
        if the respawn finds some OTHER pool emptied meanwhile,
        :meth:`_assign_role` may override the requested role."""
        if not self.roles:
            raise RuntimeError("restart_as needs a role-aware fleet")
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                "roles must be prefill/decode/both, got %r" % role)
        with self._lock:
            if index not in self._replicas:
                raise KeyError("no replica %d" % index)
            if index in self._busy:
                raise RuntimeError(
                    "replica %d is mid-restart" % index)
            self._busy.add(index)
            old = self._ids.get(index)
            handle = self._replicas.get(index)
        try:
            self.warning("re-role: restarting replica %d (%s) as %s "
                         "(controller ratio decision)", index, old,
                         role)
            if self.router is not None and old is not None:
                try:
                    self.router.remove_replica(old)
                except Exception:
                    pass
            if handle is not None:
                handle.stop()
            with self._lock:
                self._role_of[index] = role
            _rebalance_metric().labels(role=role).inc()
            self._spawn_one(index)
        finally:
            with self._lock:
                self._busy.discard(index)
        return index

    def _spawn_one(self, index):
        """Spawn replica ``index`` (next generation) and register it
        with the router; retries with capped exponential backoff when
        the spawn itself fails (the ``fleet.replica.spawn`` point)."""
        handle = None
        role = self._assign_role(index) if self.roles else None
        for attempt in range(1, self.spawn_retries + 1):
            try:
                if faults.fire("fleet.replica.spawn", key=str(index)):
                    raise RuntimeError("injected spawn drop")
                if self.roles:
                    handle = self.spawn(index, role)
                else:
                    handle = self.spawn(index)
                break
            except Exception as e:
                if attempt >= self.spawn_retries:
                    self.error("replica %d spawn failed %d times: "
                               "%r", index, attempt, e)
                    raise
                delay = min(self.spawn_cap,
                            self.spawn_delay * (2 ** (attempt - 1)))
                self.warning("replica %d spawn attempt %d failed "
                             "(%r); retrying in %.2fs", index,
                             attempt, e, delay)
                time.sleep(delay)
        rid = getattr(handle, "replica_id", None) \
            or "%s:%d" % (handle.host, handle.port)
        with self._lock:
            gen = self._generation.get(index, 0)
            self._generation[index] = gen + 1
            self._replicas[index] = handle
            self._ids[index] = rid
            if role is not None:
                self._role_of[index] = role
        if self.router is not None:
            self.router.add_replica(handle.host, handle.port,
                                    replica_id=rid)
            if gen > 0:
                self.router.stats.record_restart(rid)
        self.info("replica %d generation %d up as %s on %s:%d",
                  index, gen + 1, rid, handle.host, handle.port)
        return handle

    def _monitor(self):
        """Respawn dead replicas: deregister (the router already
        breaker-opened it after the first failed forwards), spawn the
        next generation, re-register."""
        while not self._stopping.wait(self.monitor_interval):
            with self._lock:
                dead = [i for i, h in self._replicas.items()
                        if i not in self._busy
                        and (h is None or not h.alive())]
            for index in dead:
                if self._stopping.is_set():
                    return
                with self._lock:
                    old = self._ids.get(index)
                self.warning("replica %d (%s) died — respawning",
                             index, old)
                if self.router is not None and old is not None:
                    try:
                        self.router.remove_replica(old)
                    except Exception:
                        pass
                try:
                    self._spawn_one(index)
                except Exception:
                    # spawn exhausted its retries; the next tick
                    # tries again (the index stays dead in the map)
                    with self._lock:
                        self._replicas[index] = None
            if self.rebalance_enabled and not self._stopping.is_set():
                # coverage check AFTER the respawn pass: only a pool
                # no respawn could fill triggers the active restart
                try:
                    self.rebalance()
                except Exception as e:
                    self.warning("rebalance pass failed: %r", e)

    # -- rolling restart -------------------------------------------------

    def rolling_restart(self, drain_timeout=60.0, poll=0.05):
        """Drain → stop → respawn → re-admit, one replica at a time,
        under live traffic.  Returns per-index drain/restart info;
        raises if any replica fails to drain inside
        ``drain_timeout``."""
        if self.router is None:
            raise RuntimeError("rolling restart needs a router")
        report = {}
        for index in sorted(self._replicas):
            with self._lock:
                handle = self._replicas.get(index)
                rid = self._ids.get(index)
                self._busy.add(index)
            try:
                if handle is None:
                    continue
                t0 = time.monotonic()
                self.router.drain_replica(rid)
                deadline = time.monotonic() + drain_timeout
                while True:
                    _, health = _get_json(handle.host, handle.port,
                                          "/healthz")
                    if health.get("status") == "draining" \
                            and (health.get("drained")
                                 or not health.get("in_flight")):
                        break
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            "replica %s did not drain in %.0fs "
                            "(in_flight=%s)"
                            % (rid, drain_timeout,
                               health.get("in_flight")))
                    time.sleep(poll)
                drained_s = time.monotonic() - t0
                self.router.remove_replica(rid)
                handle.stop()
                self._spawn_one(index)  # records the restart metric
                report[index] = {
                    "old": rid, "new": self.replica_id(index),
                    "drain_s": round(drained_s, 3)}
                self.info("rolling restart %d/%d: %s -> %s "
                          "(drained in %.2fs)", index + 1,
                          len(report), rid,
                          self.replica_id(index), drained_s)
            finally:
                with self._lock:
                    self._busy.discard(index)
        return report
