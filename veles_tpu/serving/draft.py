"""Model-based speculative drafting: Medusa-style multi-token heads
over the target's last hidden state.

The n-gram prompt-lookup proposer (serving/spec.py) is free but
ceiling-limited: on non-repetitive text its drafts approach empty and
speculative decoding degrades to plain decode.  The fix (ROADMAP
item 4) is a MODEL drafter in the EAGLE/Medusa lineage (Li et al.,
2024; Cai et al., 2024): ``k`` small per-position heads that read the
target's final hidden state — the [B, d] tensor the engine's
``want_hidden`` lane already computed for the LM head — and each
guess one further-future token:

- the LM head over hidden ``h_t`` (position t) predicts token t+1
  (that is the verify/decode sample itself);
- draft head ``j`` (1-based) over the SAME ``h_t`` predicts token
  ``t+1+j`` — so when the scheduler holds the hidden of the position
  BEHIND the pending token (position p-1 for pending token at p),
  head j's greedy pick drafts the token at ``p+j``, exactly draft
  slot ``d_j`` of the verify contract.

Each head is one residual SiLU block plus its own un-embedding:
``z_j = h + silu(h @ w1_j + b1_j)``, ``logits_j = z_j @ w2_j + b2_j``
(the Medusa-1 head shape).  Heads are trained against the FROZEN
target — teacher forward through every unit but the LM head yields
the hidden states, cross-entropy to the shifted token stream trains
only the head params (SGD + momentum, one jitted step) — so training
cost is a few hundred tiny steps, no target gradients.

Drafts from these heads flow through the UNCHANGED verify contract
(``accept_drafts``): a wrong draft merely rejects, so output streams
stay bit-identical to spec-off decoding no matter how good or bad
the head is — the head moves THROUGHPUT only.
"""

import numpy

import jax
import jax.numpy as jnp

from veles_tpu.models.generate import _device_params
from veles_tpu.serving.engine import hidden_supported
from veles_tpu.telemetry import track_jit


def draft_supported(forwards):
    """True when the chain can feed a model draft head: the engine's
    hidden-state lane taps the final unit's input, so the chain must
    end in a position-wise vocab head (``hidden_supported``) whose
    weights tell us (d_model, vocab)."""
    if not hidden_supported(forwards):
        return False
    w = getattr(forwards[-1], "weights", None)
    return w is not None and getattr(w, "mem", None) is not None \
        and w.mem.ndim == 2


def _make_propose(k):
    def propose(hp, hidden):
        h = hidden.astype(jnp.float32)
        z = h[:, None, :] + jax.nn.silu(
            jnp.einsum("bd,kde->bke", h, hp["w1"]) + hp["b1"])
        logits = jnp.einsum("bke,kev->bkv", z, hp["w2"]) + hp["b2"]
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return propose


def _make_train_step(forwards, k):
    last = len(forwards) - 1

    def teacher(params, toks):
        # frozen-target forward through every unit but the LM head —
        # the SAME hidden stream the engine's want_hidden lane taps
        h = toks
        for i in range(last):
            u = forwards[i]
            h = u.apply(params[i], h)
        return h.astype(jnp.float32)

    def loss_fn(hp, tparams, toks):
        h = teacher(tparams, toks)              # [B, T, d]
        b, t, _ = h.shape
        z = h[:, :, None, :] + jax.nn.silu(
            jnp.einsum("btd,kde->btke", h, hp["w1"]) + hp["b1"])
        logits = jnp.einsum("btke,kev->btkv", z, hp["w2"]) + hp["b2"]
        # head j (storage index jj = j-1) over position t predicts
        # token t+1+j = toks[t+2+jj]; positions past the window mask
        idx = jnp.arange(t)[:, None] + 2 + jnp.arange(k)[None, :]
        mask = (idx < t).astype(jnp.float32)     # [T, k]
        labels = toks[:, jnp.clip(idx, 0, t - 1)]   # [B, T, k]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, labels[..., None], axis=-1)[..., 0]
        return (nll * mask[None]).sum() \
            / jnp.maximum(mask.sum() * b, 1.0)

    grad = jax.value_and_grad(loss_fn)

    def step(hp, mom, tparams, toks, lr, momentum):
        loss, g = grad(hp, tparams, toks)
        mom = jax.tree_util.tree_map(
            lambda m, gg: momentum * m + gg, mom, g)
        hp = jax.tree_util.tree_map(
            lambda p, m: p - lr * m, hp, mom)
        return hp, mom, loss

    return step


class MedusaDraftHead:
    """``k`` per-position draft heads over a ``d_model`` hidden state
    with a ``vocab``-wide un-embedding each.  ``propose`` is the
    decode-time entry (greedy picks per head, one tiny jitted
    matmul); ``train`` fits the heads against a frozen target chain.
    The head is pure host state between calls — it pickles, and the
    scheduler treats it as an opaque ``draft_head`` argument."""

    def __init__(self, k, d_model, vocab, seed=0):
        self.k = int(k)
        self.d_model = int(d_model)
        self.vocab = int(vocab)
        if self.k < 1:
            raise ValueError("need k >= 1")
        rng = numpy.random.RandomState(int(seed))
        d, v = self.d_model, self.vocab
        # w2 starts at zero: untrained heads emit flat logits (argmax
        # 0) — harmless drafts that simply reject at verify
        self.params = {
            "w1": (rng.randn(self.k, d, d) / numpy.sqrt(d)
                   ).astype(numpy.float32),
            "b1": numpy.zeros((self.k, d), numpy.float32),
            "w2": numpy.zeros((self.k, d, v), numpy.float32),
            "b2": numpy.zeros((self.k, v), numpy.float32),
        }
        self._propose_jit = track_jit("serving.draft_step",
                                      jax.jit(_make_propose(self.k)))
        self._train_jit = None
        self._train_sig = None

    @classmethod
    def from_chain(cls, forwards, k, seed=0):
        """Size a head for ``forwards`` — d_model and vocab read off
        the chain's LM-head weights."""
        if not draft_supported(forwards):
            raise ValueError(
                "chain cannot feed a draft head (needs a trailing "
                "position-wise vocab head; see draft_supported)")
        d, v = forwards[-1].weights.mem.shape
        return cls(k, d, v, seed=seed)

    def propose(self, hidden):
        """Greedy draft tokens for a batch of hidden states:
        ``hidden`` [B, d] f32 → [B, k] int32, row n's entry j-1
        drafting the token ``j`` positions past the one row n's
        hidden already predicts.  The batch pads to a power of two so
        occupancy changes don't grow the executable ladder."""
        hidden = numpy.asarray(hidden, numpy.float32)
        b = hidden.shape[0]
        bb = 1
        while bb < b:
            bb <<= 1
        if bb != b:
            hidden = numpy.concatenate(
                [hidden, numpy.zeros((bb - b, hidden.shape[1]),
                                     numpy.float32)], axis=0)
        out = self._propose_jit(
            {n: jnp.asarray(a) for n, a in self.params.items()},
            jnp.asarray(hidden))
        return numpy.asarray(out)[:b]

    def train(self, forwards, corpus, steps=200, batch=8, window=32,
              lr=0.1, momentum=0.9, seed=0):
        """Fit the heads against the FROZEN ``forwards`` chain on
        ``corpus`` (a 1-D int token array): each step samples
        ``batch`` windows of ``window`` tokens, teacher-forwards them
        through the target (no target grads), and SGDs the head
        params on the mean masked cross-entropy.  Returns the loss
        trace (one float per step)."""
        corpus = numpy.asarray(corpus, numpy.int64).ravel()
        if len(corpus) < window + 1:
            raise ValueError("corpus shorter than one window")
        sig = (id(forwards), self.k)
        if self._train_jit is None or self._train_sig != sig:
            self._train_jit = track_jit("serving.draft_train",
                                        jax.jit(_make_train_step(
                                            forwards, self.k)))
            self._train_sig = sig
        tparams = _device_params(forwards)
        hp = {n: jnp.asarray(a) for n, a in self.params.items()}
        mom = jax.tree_util.tree_map(jnp.zeros_like, hp)
        rng = numpy.random.RandomState(int(seed))
        losses = []
        for _ in range(int(steps)):
            starts = rng.randint(0, len(corpus) - window,
                                 size=int(batch))
            toks = numpy.stack([corpus[s:s + window] for s in starts]
                               ).astype(numpy.int32)
            hp, mom, loss = self._train_jit(
                hp, mom, tparams, jnp.asarray(toks),
                jnp.float32(lr), jnp.float32(momentum))
            losses.append(float(loss))
        self.params = {n: numpy.asarray(a) for n, a in hp.items()}
        return losses

    def __getstate__(self):
        return {"k": self.k, "d_model": self.d_model,
                "vocab": self.vocab, "params": self.params}

    def __setstate__(self, state):
        self.k = state["k"]
        self.d_model = state["d_model"]
        self.vocab = state["vocab"]
        self.params = state["params"]
        self._propose_jit = track_jit("serving.draft_step",
                                      jax.jit(_make_propose(self.k)))
        self._train_jit = None
        self._train_sig = None
