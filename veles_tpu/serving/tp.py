"""Tensor-parallel serving context — shard the jitted decode steps
over a ``tp`` mesh axis so a model (weights AND paged K/V pools)
bigger than one chip's HBM still serves.

Megatron-LM-style layer sharding (Shoeybi et al., 2019) mapped onto
the serving engine: each unit that wants to shard DECLARES its own
layout through ``tp_param_spec(name, tp)`` (see
``models/transformer.py`` — wq/wk/wv and the FFN up-projection are
column-parallel, wo and the FFN down-projection row-parallel, so the
only cross-chip traffic per layer is the two output reductions XLA
inserts), and the paged K/V block pools shard **head-wise** — each
chip stores ``[num_blocks, block_size, d/tp]`` of every pool, the
per-row int8 dequant scales riding along replicated (their amax
reduces over the sharded feature axis, which is exact, so quantized
values are bit-identical to the unsharded pools).  Everything
host-side — block tables, admission, the radix trie, spec drafting,
the scheduler loop — stays replicated logic; ONLY the jitted steps
shard, which is why the integration is a context object threaded
through the compiled-step factories (the executable caches key on
``tp`` so toggling never reuses a stale trace).

The context rides :class:`~veles_tpu.serving.kv_slots.PagedKVCache`
(``cache.tp_``) into ``serving/engine.py`` and is passed explicitly
to ``serving/prefill.py`` — the full set of jitted serving entry
points (``apply_prefill_chunk``, ``apply_step_paged``,
``verify_step_paged`` and the ``serving.kv_*`` block movers) then
runs SPMD over the mesh with no per-step host logic changes.
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.parallel.mesh import build_mesh


def tp_allreduce(x, axis, size):
    """Deterministic EXPLICIT all-reduce for the collective-overlap
    decode step (``engine._make_paged_step_tp`` — per-shard bodies
    under shard_map): sums ``x`` over the ``axis`` mesh axis of
    ``size`` shards.

    tp=2 reduces with ONE collective-permute plus a local add —
    bit-identical to ``psum`` (two-operand float addition is
    order-free) and expressed as a point-to-point the compiler can
    issue asynchronously, overlapping the hop with independent
    compute (the K/V pool writeback in the decode step).  Wider
    meshes all-gather and sum in FIXED shard order, so every shard
    folds the partials identically and the result is replicated
    exactly — the property the bit-parity tests lean on."""
    if size == 2:
        return x + jax.lax.ppermute(x, axis, [(0, 1), (1, 0)])
    return jnp.sum(jax.lax.all_gather(x, axis, axis=0), axis=0)


def tp_supported(forwards, size):
    """True when every cacheable block in the chain declares a
    tensor-parallel layout that divides over ``size`` shards
    (``tp_shardable`` — heads, model dim and FFN hidden all
    divisible; MoE and int8-weight decode blocks opt out).  The
    scheduler falls back to unsharded serving otherwise."""
    if size < 2:
        return False
    has = False
    for u in forwards:
        if hasattr(u, "init_cache"):
            has = True
            fn = getattr(u, "tp_shardable", None)
            if fn is None or not fn(size):
                return False
    return has


class ServingTP:
    """One serving replica's tensor-parallel mesh + placement cache.

    ``size`` chips off the front of ``devices`` (default
    ``jax.devices()``) form a ``{"tp": size}`` mesh
    (``parallel/mesh.py`` axis conventions).  ``device_params``
    shards the chain's frozen weights by each unit's declared spec
    ONCE and caches the placement (serving weights never change, so
    repeated decode steps must not re-ship them);
    ``shard_pools`` places a paged layer's K/V pools head-wise and
    its scale arrays replicated."""

    def __init__(self, size, devices=None):
        self.size = int(size)
        if self.size < 2:
            raise ValueError("tp needs size >= 2 (got %d)" % size)
        devs = list(devices if devices is not None
                    else jax.devices())
        if len(devs) < self.size:
            raise ValueError(
                "tp=%d needs %d devices, found %d"
                % (self.size, self.size, len(devs)))
        self.mesh = build_mesh({"tp": self.size}, devs[:self.size])
        self._params = None
        self._params_for = None

    def sharding(self, spec):
        return NamedSharding(self.mesh, spec)

    def device_params(self, forwards):
        """The chain's parameters placed on the mesh: sharded where
        the unit declares a ``tp_param_spec``, replicated elsewhere.
        Computed once per chain (the ctx belongs to one scheduler,
        whose weights are frozen) — the sharded counterpart of
        ``models/generate._device_params``."""
        key = id(forwards)
        if self._params is not None and self._params_for == key:
            return self._params
        out = {}
        for i, u in enumerate(forwards):
            spec_fn = getattr(u, "tp_param_spec", None)
            layer = {}
            for name, arr in u.param_arrays().items():
                spec = spec_fn(name, self.size) \
                    if spec_fn is not None else None
                # reshard the CURRENT device value (devmem) — the
                # host .mem buffer can be stale after training until
                # a map_read, and serving must see what the solver
                # actually wrote
                layer[name] = jax.device_put(
                    arr.devmem,
                    self.sharding(spec if spec is not None else P()))
            out[i] = layer
        self._params = out
        self._params_for = key
        return out

    def shard_pools(self, pools):
        """Place one cache's per-layer pool dicts on the mesh: K/V
        buffers ``[num_blocks, block_size, d]`` shard head-wise over
        the feature axis (each chip holds ``d/tp`` of every block);
        ``*_scale`` arrays (and any axis that doesn't divide)
        replicate — scales are indexed [block, row] like the pools,
        and a replicated copy is what keeps every later block move
        (insert/gather/export) shard-layout-free."""
        out = {}
        for i, layer in pools.items():
            got = {}
            for name, a in layer.items():
                if name.endswith("_scale") or a.ndim != 3 \
                        or a.shape[-1] % self.size:
                    got[name] = jax.device_put(a, self.sharding(P()))
                else:
                    got[name] = jax.device_put(
                        a, self.sharding(P(None, None, "tp")))
            out[i] = got
        return out


def per_chip_bytes(tree):
    """The WORST per-device resident bytes of the jax arrays in a
    (possibly nested) dict tree — the honest "does this model fit one
    chip" measure: sharded arrays count ``nbytes / tp`` per chip,
    replicated arrays count in full on every chip.  This is the
    number ``bench.py tp`` holds fixed while growing d_model."""
    acc = {}

    def visit(x):
        if isinstance(x, dict):
            for v in x.values():
                visit(v)
        elif hasattr(x, "addressable_shards"):
            for sh in x.addressable_shards:
                acc[sh.device.id] = acc.get(sh.device.id, 0) \
                    + sh.data.nbytes
        elif hasattr(x, "nbytes"):   # plain single-device array
            acc[0] = acc.get(0, 0) + x.nbytes

    visit(tree)
    return max(acc.values()) if acc else 0
