"""Continuous-batching inference scheduler.

Requests queue on :meth:`InferenceScheduler.submit` (any thread) and
are decoded by ONE background loop (all jax work — ``Array.devmem``
uploads and the compile caches are not thread-safe against concurrent
mutation, and a single loop is what lets every in-flight request share
one compiled step):

1. **admit** — while capacity allows, the oldest queued request
   claims a slot.  Under the default PAGED KV cache
   (:class:`serving.kv_slots.PagedKVCache`) admission is
   memory-proportional: the request also claims its whole block
   budget (``ceil((prompt + steps) / block_size)`` blocks), so short
   requests pack many more concurrent streams into the same HBM than
   the dense window-per-slot layout;
2. **prefill** — prompts up to ``prefill_chunk`` prefill in ONE
   compiled pass; longer prompts prefill in ``prefill_chunk``-token
   CHUNKS, at most one chunk per loop iteration, INTERLEAVED with the
   decode step below (Sarathi-style chunked prefill) — a joining long
   prompt stalls in-flight decode streams by one chunk per iteration,
   not by its whole prefill, which flattens the TTFT tail of short
   requests stuck behind long ones.  Either way the K/V staging row
   is inserted into the cache and the first token samples from the
   final logits (the TTFT edge);
3. **step** — active slots advance one token through the shared
   compiled step.  The paged path packs ONLY the active slots into a
   power-of-two occupancy bucket and bounds attention by a
   power-of-two block bucket over the deepest request
   (:func:`serving.engine.paged_decode_step`), so a half-empty batch
   of shallow requests pays neither full-batch nor full-window
   compute; the dense fallback runs the fixed full-slot step;
4. **retire** — a slot that generated its stop token or hit its step
   limit completes its future and frees slot + blocks at the token
   boundary, where the next queued request joins.

Admission control: a full queue raises :class:`QueueFullError` (HTTP
503) at submit; a request still queued past its deadline fails with
:class:`DeadlineExceededError` (HTTP 408).  Greedy requests keep
exact determinism (each request's attention sees only its own cache
rows/blocks, and sampling is row-wise, so token streams are
independent of slot placement, packing order and co-tenants);
sampled requests are reproducible per seed — though the stream
differs from the single-user ``generate()`` path's (one fold per
generated token here vs one split per lockstep buffer position
there).

Config knobs (``root.common.serving.*``, overridable per scheduler):
``kv`` ("paged"/"dense"), ``block_size`` (tokens per KV block,
default 16), ``kv_blocks`` (pool capacity in blocks; default the
dense-equivalent ``max_slots · ceil(window / block_size)``) and
``prefill_chunk`` (chunk width in tokens, rounded up to a power of
two; 0 disables chunking, default 64).
"""

import collections
import concurrent.futures
import os
import threading
import time

import numpy

from veles_tpu.logger import Logger
from veles_tpu.serving.engine import (
    first_tokens, paged_decode_step, slot_decode_step)
from veles_tpu.serving.kv_slots import (
    PagedKVCache, SlotKVCache, paged_supported)
from veles_tpu.serving.metrics import ServingMetrics
from veles_tpu.serving.prefill import (
    chunked_supported, prefill, prefill_chunk, serving_supported,
    serving_window)


class SchedulerError(Exception):
    """Base serving failure (maps to HTTP 500)."""
    http_status = 500


class QueueFullError(SchedulerError):
    """Admission control: queue-depth cap hit (HTTP 503)."""
    http_status = 503


class DeadlineExceededError(SchedulerError):
    """Admission control: queued past the deadline (HTTP 408)."""
    http_status = 408


def _bucket(n, floor, cap):
    """Pad widths/counts to power-of-two buckets so the compiled
    executable count stays O(log) across arbitrary clients."""
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return min(b, cap)


def _serving_conf(name, default):
    from veles_tpu.config import root
    return root.common.serving.get(name, default)


class _Request(object):
    __slots__ = ("prompt", "steps", "temperature", "top_k",
                 "stop_token", "seed", "deadline", "future", "slot",
                 "generated", "t_submit", "t_admit", "t_first",
                 "pf_caches", "pf_off", "pf_width", "pf_chunk")

    def __init__(self, prompt, steps, temperature, top_k, stop_token,
                 seed, deadline):
        self.prompt = prompt
        self.steps = steps
        self.temperature = temperature
        self.top_k = top_k
        self.stop_token = stop_token
        self.seed = seed
        self.deadline = deadline
        self.future = concurrent.futures.Future()
        self.slot = None
        self.generated = []
        self.t_submit = time.monotonic()
        self.t_admit = None
        self.t_first = None
        # chunked-prefill progress (None while queued / one-shot)
        self.pf_caches = None
        self.pf_off = 0
        self.pf_width = 0
        self.pf_chunk = 0


class InferenceScheduler(Logger):
    """Continuous-batching decode service over a forward chain.

    ``max_slots`` — concurrent requests decoding per step;
    ``window`` — per-request length bound, ``prompt_len + steps <=
    window`` (default: the chain's positional table);
    ``max_queue`` — waiting-request cap beyond the slots (503 above);
    ``queue_timeout`` — default admission deadline in seconds (408
    for requests still queued past it);
    ``prefill_bucket`` — smallest compiled prefill width;
    ``kv`` / ``block_size`` / ``kv_blocks`` / ``prefill_chunk`` —
    paged-cache and chunked-prefill knobs (None defers to
    ``root.common.serving.*``; see the module docstring)."""

    def __init__(self, forwards, max_slots=4, window=None,
                 max_queue=32, queue_timeout=30.0, prefill_bucket=8,
                 kv=None, block_size=None, kv_blocks=None,
                 prefill_chunk=None, warm_buckets=None):
        super(InferenceScheduler, self).__init__()
        if not serving_supported(forwards):
            raise ValueError(
                "chain cannot serve through the slot scheduler (needs "
                "causal cacheable blocks with apply_prefill/"
                "apply_step_slots; see serving_supported)")
        window = window or serving_window(forwards)
        if not window or int(window) < 2:
            raise ValueError(
                "no usable decode window: pass window= (the chain has "
                "no learned positional table to derive it from)")
        self.forwards = forwards
        self.max_slots = int(max_slots)
        self.window = int(window)
        self.max_queue = int(max_queue)
        self.queue_timeout = float(queue_timeout)
        self.prefill_bucket = int(prefill_bucket)
        kv = kv or _serving_conf("kv", "paged")
        if kv not in ("paged", "dense"):
            raise ValueError("kv must be 'paged' or 'dense'")
        if kv == "paged" and not paged_supported(forwards):
            self.info("chain has no paged decode step; falling back "
                      "to the dense slot cache")
            kv = "dense"
        self.kv = kv
        self.block_size = int(
            block_size or _serving_conf("block_size", 16))
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.blocks_per_slot = -(-self.window // self.block_size)
        if kv_blocks is None:
            kv_blocks = _serving_conf("kv_blocks", None)
        self.kv_blocks = int(
            kv_blocks or self.max_slots * self.blocks_per_slot) \
            if self.kv == "paged" else 0
        chunk = prefill_chunk if prefill_chunk is not None \
            else _serving_conf("prefill_chunk", 64)
        chunk = int(chunk or 0)
        if chunk and not chunked_supported(forwards):
            self.info("chain cannot prefill in chunks; long prompts "
                      "will prefill one-shot")
            chunk = 0
        #: chunk widths ride compiled executables — power-of-two
        self.prefill_chunk = _bucket(chunk, 1, 1 << 30) if chunk else 0
        self.warm_buckets = bool(
            _serving_conf("warm_buckets", True)
            if warm_buckets is None else warm_buckets)
        self.stats = ServingMetrics()
        self._queue = collections.deque()
        self._active = {}            # slot -> _Request (decoding)
        self._prefilling = []        # admitted, mid-chunked-prefill
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._thread = None
        self._ready = threading.Event()
        self.cache_ = None           # set by the loop thread

    # -- client side ----------------------------------------------------

    def start(self):
        """Warm the device params (single-threaded — Array.devmem's
        lazy upload is not re-entrant), start the decode loop and
        block until it is READY — cache built and the paged-step
        bucket ladder compiled — so traffic never eats warmup
        compiles as decode stalls."""
        with self._lock:  # two racing start()s must not spawn two loops
            if self._thread is not None:
                started = True
            else:
                started = False
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="serving-scheduler")
        if started:
            self._ready.wait(600)
            return self
        try:
            for u in self.forwards:
                for arr in u.param_arrays().values():
                    arr.devmem
            self._thread.start()
        except BaseException:
            with self._lock:  # release the claim so start() can retry
                self._thread = None
            raise
        self._ready.wait(600)
        return self

    def submit(self, prompt, steps, temperature=0.0, top_k=0,
               seed=None, stop_token=None, timeout=None):
        """Queue one sequence for decoding; returns a Future whose
        result is the full token list (prompt + generated, ending at
        the first generated stop token if one fired).

        Raises ``ValueError`` on malformed requests (client errors),
        :class:`QueueFullError` when admission control rejects."""
        prompt = [int(t) for t in prompt]
        steps = int(steps)
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if steps < 1:
            raise ValueError("steps must be >= 1")
        if len(prompt) + steps > self.window:
            raise ValueError(
                "prompt_len + steps = %d exceeds the serving window "
                "(%d)" % (len(prompt) + steps, self.window))
        if self.kv == "paged":
            need = -(-(len(prompt) + steps) // self.block_size)
            if need > self.kv_blocks:
                raise ValueError(
                    "request needs %d KV blocks > pool capacity %d "
                    "(kv_blocks)" % (need, self.kv_blocks))
        temperature = float(temperature or 0.0)
        top_k = int(top_k or 0)
        if top_k and not temperature:
            raise ValueError(
                "top_k only applies to sampling — set temperature > 0")
        if seed is None:
            # unpinned sampling must draw fresh tokens per request
            seed = int.from_bytes(os.urandom(4), "little")
        req = _Request(
            prompt, steps, temperature, top_k,
            int(stop_token) if stop_token is not None else None,
            int(seed) & 0xFFFFFFFF,
            time.monotonic() + float(timeout or self.queue_timeout))
        with self._wake:
            if self._closed:
                raise SchedulerError("scheduler is closed")
            if len(self._queue) >= self.max_queue:
                self.stats.record_reject(len(self._queue))
                raise QueueFullError(
                    "serving queue full (%d waiting)"
                    % len(self._queue))
            self.stats.record_submit()
            self._queue.append(req)
            self._wake.notify()
        return req.future

    def _kv_snapshot(self):
        out = {"kv_mode": self.kv,
               "prefill_chunk": self.prefill_chunk,
               "prefilling": len(self._prefilling)}
        cache = self.cache_
        if self.kv == "paged":
            out["kv_block_size"] = self.block_size
            out["kv_blocks_total"] = self.kv_blocks
            # the loop thread owns the free lists; these reads are
            # monitoring-grade (len() is atomic enough for a gauge)
            out["kv_blocks_used"] = \
                cache.used_blocks if cache is not None else 0
            out["kv_blocks_free"] = \
                cache.free_blocks if cache is not None \
                else self.kv_blocks
        return out

    def metrics(self):
        with self._lock:
            depth, active = len(self._queue), len(self._active)
        snap = self.stats.snapshot(queue_depth=depth,
                                   active_slots=active,
                                   max_slots=self.max_slots,
                                   kv=self._kv_snapshot())
        snap["window"] = self.window
        return snap

    def close(self):
        """Stop the loop and fail every unfinished request."""
        with self._wake:
            if self._closed:
                return
            self._closed = True
            self._wake.notify()
        if self._thread is not None:
            self._thread.join(30)
        err = SchedulerError("scheduler closed")
        with self._lock:
            pending = list(self._queue) + list(self._prefilling) \
                + list(self._active.values())
            self._queue.clear()
            self._prefilling = []
            self._active.clear()
        for req in pending:
            if not req.future.done():
                req.future.set_exception(err)

    # -- decode loop ----------------------------------------------------

    def _make_cache(self):
        if self.kv == "paged":
            return PagedKVCache(self.forwards, self.max_slots,
                                self.window,
                                block_size=self.block_size,
                                kv_blocks=self.kv_blocks)
        return SlotKVCache(self.forwards, self.max_slots, self.window)

    def _warm_paged(self, cache):
        """Compile the paged step's (occupancy, depth) bucket ladder
        BEFORE traffic: a bucket's first compile would otherwise land
        inside live serving as a multi-second decode stall (exactly
        the tail latency the buckets exist to remove).  The dummy
        batches are all padding rows — token 0 at position 0 through
        an all-zero block table, i.e. reads and writes confined to
        the reserved trash block."""
        buckets = sorted({_bucket(n, 1, self.max_slots)
                          for n in range(1, self.max_slots + 1)})
        depths = sorted({_bucket(n, 1, cache.blocks_per_slot)
                         for n in range(1, cache.blocks_per_slot + 1)})
        t0 = time.monotonic()
        for b in buckets:
            for t in depths:
                paged_decode_step(
                    self.forwards, cache,
                    numpy.zeros((b, 1), numpy.int32),
                    numpy.zeros((b,), numpy.int32),
                    numpy.zeros((b, t), numpy.int32),
                    numpy.zeros((b,), numpy.float32),
                    numpy.zeros((b,), numpy.int32),
                    numpy.zeros((b,), numpy.uint32),
                    numpy.zeros((b,), numpy.int32))
        self.info("paged-step warmup: %d occupancy x %d depth "
                  "buckets in %.2fs", len(buckets), len(depths),
                  time.monotonic() - t0)

    def _loop(self):
        try:
            cache = self._make_cache()
            if self.kv == "paged" and self.warm_buckets:
                self._warm_paged(cache)
            self.cache_ = cache
        except Exception as e:  # surface init failures to clients
            with self._wake:
                self._closed = True
                pending = list(self._queue)
                self._queue.clear()
            self._ready.set()
            for req in pending:
                req.future.set_exception(SchedulerError(repr(e)))
            raise
        self._ready.set()
        while True:
            with self._wake:
                while not self._closed and not self._queue \
                        and not self._active and not self._prefilling:
                    self._wake.wait()
                if self._closed:
                    return
                self._expire_locked()
                admits = []
                while self._queue and cache.can_admit(
                        len(self._queue[0].prompt)
                        + self._queue[0].steps):
                    req = self._queue.popleft()
                    req.slot = cache.alloc(len(req.prompt)
                                           + req.steps)
                    admits.append(req)
            # jax work OUTSIDE the lock: submit() must never block on
            # a device step
            self._sync_kv_gauges(cache)
            for req in admits:
                self._begin_admit(req, cache)
            if self._prefilling:
                self._prefill_tick(cache)
            if self._active:
                self._step(cache)

    def _sync_kv_gauges(self, cache):
        if self.kv == "paged":
            self.stats.set_kv_blocks(cache.used_blocks,
                                     cache.free_blocks)

    def _expire_locked(self):
        now = time.monotonic()
        kept = collections.deque()
        while self._queue:
            req = self._queue.popleft()
            if req.deadline is not None and now > req.deadline:
                queued_ms = (now - req.t_submit) * 1e3
                self.stats.record_expire(queued_ms)
                req.future.set_exception(DeadlineExceededError(
                    "queued %.0f ms without a free slot" % queued_ms))
            else:
                kept.append(req)
        self._queue = kept

    def _staging_width(self, p_len, chunk):
        """Width of the batch-1 staging K/V row a prompt prefills
        into: the power-of-two bucket of the prompt, floored so it
        tiles both the chunk width and (paged) the block size."""
        bs = self.block_size if self.kv == "paged" else 1
        floor = max(self.prefill_bucket, bs, chunk or 1)
        return _bucket(p_len, floor, 1 << 30)

    def _begin_admit(self, req, cache):
        """Route one joining request: short prompts prefill one-shot;
        long prompts start the chunked-prefill ride-along."""
        req.t_admit = time.monotonic()
        p_len = len(req.prompt)
        chunk = self.prefill_chunk
        if not chunk or p_len <= chunk:
            self._admit_oneshot(req, cache)
            return
        from veles_tpu import dtypes
        req.pf_chunk = chunk
        req.pf_width = self._staging_width(p_len, chunk)
        req.pf_off = 0
        try:
            req.pf_caches = {
                i: u.init_cache(1, req.pf_width,
                                dtypes.compute_dtype())
                for i, u in enumerate(self.forwards)
                if hasattr(u, "init_cache")}
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        with self._lock:  # close() swaps the list under the same lock
            self._prefilling.append(req)

    def _admit_oneshot(self, req, cache):
        """Prefill one joining request in a single compiled pass and
        emit its first token (the TTFT edge)."""
        p_len = len(req.prompt)
        width = self._staging_width(p_len, 0)
        # the PROMPT array stays inside the positional table; the
        # staging cache may be wider (insert trims it back)
        p_w = min(width, max(self.window, p_len))
        padded = numpy.zeros((1, p_w), numpy.int32)
        padded[0, :p_len] = req.prompt
        try:
            row_caches, last = prefill(
                self.forwards, padded, prompt_lens=[p_len],
                window=width)
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        self._finish_admit(req, cache, row_caches, last)

    def _prefill_tick(self, cache):
        """Advance the oldest mid-prefill request by ONE chunk — the
        per-iteration decode-stall bound; the decode step for every
        in-flight stream runs right after, in the same iteration."""
        with self._lock:
            req = self._prefilling[0]
        p_len = len(req.prompt)
        c = req.pf_chunk
        off = req.pf_off
        end = min(off + c, p_len)
        clen = end - off
        padded = numpy.zeros((1, c), numpy.int32)
        padded[0, :clen] = req.prompt[off:end]
        kw = _bucket(off + c, c, req.pf_width)
        t0 = time.perf_counter()
        try:
            req.pf_caches, last = prefill_chunk(
                self.forwards, padded, off, [clen], req.pf_caches,
                key_width=kw)
        except Exception as e:
            with self._lock:
                if req in self._prefilling:
                    self._prefilling.remove(req)
            self._retire(req, cache, error=e)
            return
        self.stats.record_prefill_chunk(
            clen, (time.perf_counter() - t0) * 1e3)
        req.pf_off = end
        if end >= p_len:
            with self._lock:
                if req in self._prefilling:
                    self._prefilling.remove(req)
            self._finish_admit(req, cache, req.pf_caches, last)

    def _finish_admit(self, req, cache, row_caches, last):
        """Insert the prefilled staging row and emit the first
        token."""
        try:
            cache.insert(req.slot, row_caches, len(req.prompt))
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        req.pf_caches = None
        tok = int(numpy.asarray(first_tokens(
            last, [req.temperature], [req.top_k], [req.seed]))[0])
        req.generated.append(tok)
        req.t_first = time.monotonic()
        self.stats.record_first_token(
            (req.t_first - req.t_submit) * 1e3,
            (req.t_admit - req.t_submit) * 1e3)
        with self._lock:
            self._active[req.slot] = req
        self._maybe_finish(req, cache)

    def _step(self, cache):
        """Advance every active request one token through the shared
        compiled step, then retire finished ones at the boundary."""
        with self._lock:
            active = dict(self._active)
        if not active:
            return
        if self.kv == "paged":
            self._step_paged(cache, active)
        else:
            self._step_dense(cache, active)

    def _fill_row(self, arrays, j, req):
        toks, pos, temps, topks, seeds, counts = arrays
        toks[j, 0] = req.generated[-1]
        pos[j] = len(req.prompt) + len(req.generated) - 1
        temps[j] = req.temperature
        topks[j] = req.top_k
        seeds[j] = req.seed
        counts[j] = len(req.generated)

    def _step_paged(self, cache, active):
        """Packed step: ONLY the active slots ride the batch, padded
        to a power-of-two occupancy bucket; the attended range is the
        power-of-two block bucket of the deepest request."""
        slots = sorted(active)
        n = len(slots)
        b = _bucket(n, 1, self.max_slots)
        bs = cache.block_size
        deepest = max(len(active[s].prompt) + len(active[s].generated)
                      for s in slots)
        t = _bucket(-(-deepest // bs), 1, cache.blocks_per_slot)
        toks = numpy.zeros((b, 1), numpy.int32)
        pos = numpy.zeros((b,), numpy.int32)
        temps = numpy.zeros((b,), numpy.float32)
        topks = numpy.zeros((b,), numpy.int32)
        seeds = numpy.zeros((b,), numpy.uint32)
        counts = numpy.zeros((b,), numpy.int32)
        tables = numpy.zeros((b, t), numpy.int32)
        arrays = (toks, pos, temps, topks, seeds, counts)
        for j, slot in enumerate(slots):
            self._fill_row(arrays, j, active[slot])
        tables[:n] = cache.table_rows(slots, t)
        nxt = numpy.asarray(paged_decode_step(
            self.forwards, cache, toks, pos, tables, temps, topks,
            seeds, counts))
        self.stats.record_step(n, b)
        for j, slot in enumerate(slots):
            req = active[slot]
            req.generated.append(int(nxt[j]))
            self._maybe_finish(req, cache)

    def _step_dense(self, cache, active):
        """Legacy full-batch step: free slots decode garbage rows."""
        s = self.max_slots
        toks = numpy.zeros((s, 1), numpy.int32)
        pos = numpy.zeros((s,), numpy.int32)
        temps = numpy.zeros((s,), numpy.float32)
        topks = numpy.zeros((s,), numpy.int32)
        seeds = numpy.zeros((s,), numpy.uint32)
        counts = numpy.zeros((s,), numpy.int32)
        arrays = (toks, pos, temps, topks, seeds, counts)
        for slot, req in active.items():
            self._fill_row(arrays, slot, req)
        nxt = numpy.asarray(slot_decode_step(
            self.forwards, cache, toks, pos, temps, topks, seeds,
            counts))
        self.stats.record_step(len(active), s)
        for slot, req in active.items():
            req.generated.append(int(nxt[slot]))
            self._maybe_finish(req, cache)

    def _maybe_finish(self, req, cache, error=None):
        done = error is not None \
            or len(req.generated) >= req.steps \
            or (req.stop_token is not None
                and req.generated[-1] == req.stop_token)
        if done:
            self._retire(req, cache, error=error)

    def _retire(self, req, cache, error=None):
        with self._lock:
            self._active.pop(req.slot, None)
        cache.release(req.slot)
        self._sync_kv_gauges(cache)
        if error is not None:
            req.future.set_exception(
                error if isinstance(error, SchedulerError)
                else SchedulerError(repr(error)))
            return
        now = time.monotonic()
        self.stats.record_complete(
            len(req.generated), now - req.t_submit,
            (req.t_first - req.t_submit) * 1e3,
            (req.t_admit - req.t_submit) * 1e3)
        req.future.set_result(list(req.prompt) + req.generated)
