"""Continuous-batching inference scheduler.

Requests queue on :meth:`InferenceScheduler.submit` (any thread) and
are decoded by ONE background loop (all jax work — ``Array.devmem``
uploads and the compile caches are not thread-safe against concurrent
mutation, and a single loop is what lets every in-flight request share
one compiled step):

1. **admit** — while free slots exist, the oldest queued request
   claims one: its prompt prefills in ONE compiled pass (bucketed
   widths bound the executable count), the K/V row is inserted into
   the slot cache, and its first token samples from the prefill
   logits (that's the TTFT edge);
2. **step** — all active slots advance one token through the shared
   compiled step (:func:`serving.engine.slot_decode_step`) — requests
   at different depths, temperatures and seeds genuinely interleave;
3. **retire** — a slot that generated its stop token or hit its step
   limit completes its future and frees at the token boundary, where
   the next queued request joins.

Admission control: a full queue raises :class:`QueueFullError` (HTTP
503) at submit; a request still queued past its deadline fails with
:class:`DeadlineExceededError` (HTTP 408).  Greedy requests keep
exact determinism (each slot's attention sees only its own cache
row); sampled requests are reproducible per seed — though the stream
differs from the single-user ``generate()`` path's (one fold per
generated token here vs one split per lockstep buffer position
there).
"""

import collections
import concurrent.futures
import os
import threading
import time

import numpy

from veles_tpu.logger import Logger
from veles_tpu.serving.engine import first_tokens, slot_decode_step
from veles_tpu.serving.kv_slots import SlotKVCache
from veles_tpu.serving.metrics import ServingMetrics
from veles_tpu.serving.prefill import (
    prefill, serving_supported, serving_window)


class SchedulerError(Exception):
    """Base serving failure (maps to HTTP 500)."""
    http_status = 500


class QueueFullError(SchedulerError):
    """Admission control: queue-depth cap hit (HTTP 503)."""
    http_status = 503


class DeadlineExceededError(SchedulerError):
    """Admission control: queued past the deadline (HTTP 408)."""
    http_status = 408


def _bucket(n, floor, cap):
    """Pad prompt widths to power-of-two buckets so the compiled
    prefill count stays O(log window) across arbitrary clients."""
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return min(b, cap)


class _Request(object):
    __slots__ = ("prompt", "steps", "temperature", "top_k",
                 "stop_token", "seed", "deadline", "future", "slot",
                 "generated", "t_submit", "t_admit", "t_first")

    def __init__(self, prompt, steps, temperature, top_k, stop_token,
                 seed, deadline):
        self.prompt = prompt
        self.steps = steps
        self.temperature = temperature
        self.top_k = top_k
        self.stop_token = stop_token
        self.seed = seed
        self.deadline = deadline
        self.future = concurrent.futures.Future()
        self.slot = None
        self.generated = []
        self.t_submit = time.monotonic()
        self.t_admit = None
        self.t_first = None


class InferenceScheduler(Logger):
    """Continuous-batching decode service over a forward chain.

    ``max_slots`` — concurrent requests decoding per step;
    ``window`` — slot cache width (default: the chain's positional
    table; a request needs ``prompt_len + steps <= window``);
    ``max_queue`` — waiting-request cap beyond the slots (503 above);
    ``queue_timeout`` — default admission deadline in seconds (408
    for requests still queued past it);
    ``prefill_bucket`` — smallest compiled prefill width.
    """

    def __init__(self, forwards, max_slots=4, window=None,
                 max_queue=32, queue_timeout=30.0, prefill_bucket=8):
        super(InferenceScheduler, self).__init__()
        if not serving_supported(forwards):
            raise ValueError(
                "chain cannot serve through the slot scheduler (needs "
                "causal cacheable blocks with apply_prefill/"
                "apply_step_slots; see serving_supported)")
        window = window or serving_window(forwards)
        if not window or int(window) < 2:
            raise ValueError(
                "no usable decode window: pass window= (the chain has "
                "no learned positional table to derive it from)")
        self.forwards = forwards
        self.max_slots = int(max_slots)
        self.window = int(window)
        self.max_queue = int(max_queue)
        self.queue_timeout = float(queue_timeout)
        self.prefill_bucket = int(prefill_bucket)
        self.stats = ServingMetrics()
        self._queue = collections.deque()
        self._active = {}            # slot -> _Request
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._thread = None

    # -- client side ----------------------------------------------------

    def start(self):
        """Warm the device params (single-threaded — Array.devmem's
        lazy upload is not re-entrant) and start the decode loop."""
        if self._thread is not None:
            return self
        for u in self.forwards:
            for arr in u.param_arrays().values():
                arr.devmem
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serving-scheduler")
        self._thread.start()
        return self

    def submit(self, prompt, steps, temperature=0.0, top_k=0,
               seed=None, stop_token=None, timeout=None):
        """Queue one sequence for decoding; returns a Future whose
        result is the full token list (prompt + generated, ending at
        the first generated stop token if one fired).

        Raises ``ValueError`` on malformed requests (client errors),
        :class:`QueueFullError` when admission control rejects."""
        prompt = [int(t) for t in prompt]
        steps = int(steps)
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if steps < 1:
            raise ValueError("steps must be >= 1")
        if len(prompt) + steps > self.window:
            raise ValueError(
                "prompt_len + steps = %d exceeds the serving window "
                "(%d)" % (len(prompt) + steps, self.window))
        temperature = float(temperature or 0.0)
        top_k = int(top_k or 0)
        if top_k and not temperature:
            raise ValueError(
                "top_k only applies to sampling — set temperature > 0")
        if seed is None:
            # unpinned sampling must draw fresh tokens per request
            seed = int.from_bytes(os.urandom(4), "little")
        req = _Request(
            prompt, steps, temperature, top_k,
            int(stop_token) if stop_token is not None else None,
            int(seed) & 0xFFFFFFFF,
            time.monotonic() + float(timeout or self.queue_timeout))
        with self._wake:
            if self._closed:
                raise SchedulerError("scheduler is closed")
            if len(self._queue) >= self.max_queue:
                self.stats.record_reject(len(self._queue))
                raise QueueFullError(
                    "serving queue full (%d waiting)"
                    % len(self._queue))
            self.stats.record_submit()
            self._queue.append(req)
            self._wake.notify()
        return req.future

    def metrics(self):
        with self._lock:
            depth, active = len(self._queue), len(self._active)
        snap = self.stats.snapshot(queue_depth=depth,
                                   active_slots=active,
                                   max_slots=self.max_slots)
        snap["window"] = self.window
        return snap

    def close(self):
        """Stop the loop and fail every unfinished request."""
        with self._wake:
            if self._closed:
                return
            self._closed = True
            self._wake.notify()
        if self._thread is not None:
            self._thread.join(30)
        err = SchedulerError("scheduler closed")
        with self._lock:
            pending = list(self._queue) + list(self._active.values())
            self._queue.clear()
            self._active.clear()
        for req in pending:
            if not req.future.done():
                req.future.set_exception(err)

    # -- decode loop ----------------------------------------------------

    def _loop(self):
        try:
            cache = SlotKVCache(self.forwards, self.max_slots,
                                self.window)
        except Exception as e:  # surface init failures to clients
            with self._wake:
                self._closed = True
                pending = list(self._queue)
                self._queue.clear()
            for req in pending:
                req.future.set_exception(SchedulerError(repr(e)))
            raise
        while True:
            with self._wake:
                while not self._closed and not self._queue \
                        and not self._active:
                    self._wake.wait()
                if self._closed:
                    return
                self._expire_locked()
                admits = []
                while self._queue and cache.free_slots:
                    req = self._queue.popleft()
                    req.slot = cache.alloc()
                    self._active[req.slot] = req
                    admits.append(req)
            # jax work OUTSIDE the lock: submit() must never block on
            # a device step
            for req in admits:
                self._admit(req, cache)
            if self._active:
                self._step(cache)

    def _expire_locked(self):
        now = time.monotonic()
        kept = collections.deque()
        while self._queue:
            req = self._queue.popleft()
            if req.deadline is not None and now > req.deadline:
                queued_ms = (now - req.t_submit) * 1e3
                self.stats.record_expire(queued_ms)
                req.future.set_exception(DeadlineExceededError(
                    "queued %.0f ms without a free slot" % queued_ms))
            else:
                kept.append(req)
        self._queue = kept

    def _admit(self, req, cache):
        """Prefill one joining request into its slot and emit its
        first token (the TTFT edge)."""
        req.t_admit = time.monotonic()
        p_len = len(req.prompt)
        width = _bucket(p_len, self.prefill_bucket, self.window)
        padded = numpy.zeros((1, width), numpy.int32)
        padded[0, :p_len] = req.prompt
        try:
            row_caches, last = prefill(
                self.forwards, padded, prompt_lens=[p_len],
                window=self.window)
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        cache.insert(req.slot, row_caches)
        tok = int(numpy.asarray(first_tokens(
            last, [req.temperature], [req.top_k], [req.seed]))[0])
        req.generated.append(tok)
        req.t_first = time.monotonic()
        self.stats.record_first_token(
            (req.t_first - req.t_submit) * 1e3,
            (req.t_admit - req.t_submit) * 1e3)
        self._maybe_finish(req, cache)

    def _step(self, cache):
        """Advance every active slot one token through the shared
        compiled step, then retire finished slots at the boundary."""
        s = self.max_slots
        toks = numpy.zeros((s, 1), numpy.int32)
        pos = numpy.zeros((s,), numpy.int32)
        temps = numpy.zeros((s,), numpy.float32)
        topks = numpy.zeros((s,), numpy.int32)
        seeds = numpy.zeros((s,), numpy.uint32)
        counts = numpy.zeros((s,), numpy.int32)
        with self._lock:
            active = dict(self._active)
        if not active:
            return
        for slot, req in active.items():
            toks[slot, 0] = req.generated[-1]
            pos[slot] = len(req.prompt) + len(req.generated) - 1
            temps[slot] = req.temperature
            topks[slot] = req.top_k
            seeds[slot] = req.seed
            counts[slot] = len(req.generated)
        nxt = numpy.asarray(slot_decode_step(
            self.forwards, cache, toks, pos, temps, topks, seeds,
            counts))
        self.stats.record_step(len(active), s)
        for slot, req in active.items():
            req.generated.append(int(nxt[slot]))
            self._maybe_finish(req, cache)

    def _maybe_finish(self, req, cache, error=None):
        done = error is not None \
            or len(req.generated) >= req.steps \
            or (req.stop_token is not None
                and req.generated[-1] == req.stop_token)
        if done:
            self._retire(req, cache, error=error)

    def _retire(self, req, cache, error=None):
        with self._lock:
            self._active.pop(req.slot, None)
        cache.release(req.slot)
        if error is not None:
            req.future.set_exception(
                error if isinstance(error, SchedulerError)
                else SchedulerError(repr(error)))
            return
        now = time.monotonic()
        self.stats.record_complete(
            len(req.generated), now - req.t_submit,
            (req.t_first - req.t_submit) * 1e3,
            (req.t_admit - req.t_submit) * 1e3)
        req.future.set_result(list(req.prompt) + req.generated)
